"""--default-scheduler-config semantics: the KubeSchedulerConfiguration file's
plugin enable/disable lists and score weights govern scheduling (the reference
threads the file through the kube-scheduler options machinery,
pkg/simulator/utils.go:303-381); anything the engine cannot honor raises
ConfigError instead of silently using defaults."""

import copy

import pytest

from open_simulator_tpu.api.schedconfig import (
    DEFAULT_SCHEDULER_CONFIG,
    SchedulerConfig,
    parse_scheduler_config,
)
from open_simulator_tpu.api.v1alpha1 import ConfigError
from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod


def write(tmp_path, text):
    p = tmp_path / "sched.yaml"
    p.write_text(text)
    return str(p)


HEADER = "apiVersion: kubescheduler.config.k8s.io/v1beta1\nkind: KubeSchedulerConfiguration\n"


def test_parse_empty_and_default(tmp_path):
    cfg = parse_scheduler_config(write(tmp_path, HEADER))
    assert cfg == DEFAULT_SCHEDULER_CONFIG
    assert cfg.score_weights["PodTopologySpread"] == 2.0
    assert cfg.score_weights["NodePreferAvoidPods"] == 10000.0


def test_parse_weights_and_disables(tmp_path):
    cfg = parse_scheduler_config(write(tmp_path, HEADER + """
profiles:
  - schedulerName: default-scheduler
    plugins:
      score:
        enabled:
          - name: Simon
            weight: 5
          - name: ImageLocality   # weight 0 -> framework zero->1 rule
        disabled:
          - name: TaintToleration
      filter:
        disabled:
          - name: NodePorts
          - name: TaintToleration
"""))
    assert cfg.score_weights["Simon"] == 5.0
    assert cfg.score_weights["ImageLocality"] == 1.0
    assert cfg.score_weights["TaintToleration"] == 0.0
    assert cfg.disabled_kernel_filters == frozenset({"NodePorts"})
    assert cfg.disabled_encoder_filters == frozenset({"TaintToleration"})


def test_parse_wildcard_disable(tmp_path):
    cfg = parse_scheduler_config(write(tmp_path, HEADER + """
profiles:
  - plugins:
      score:
        disabled:
          - name: "*"
        enabled:
          - name: Simon
            weight: 3
"""))
    assert cfg.score_weights["Simon"] == 3.0
    assert all(w == 0.0 for k, w in cfg.score_weights.items() if k != "Simon")


@pytest.mark.parametrize("body,msg", [
    ("percentageOfNodesToScore: 50\n", "percentageOfNodesToScore"),
    ("extenders:\n  - urlPrefix: http://x\n", "extenders"),
    ("profiles:\n  - schedulerName: other\n", "schedulerName"),
    ("profiles:\n  - plugins:\n      score:\n        enabled:\n          - name: NoSuchPlugin\n",
     "NoSuchPlugin"),
    ("profiles:\n  - pluginConfig:\n      - name: Simon\n", "pluginConfig"),
    ("profiles:\n  - plugins:\n      preFilter:\n        disabled:\n          - name: NodePorts\n",
     "preFilter"),
    ("someUnknownField: 3\n", "someUnknownField"),
])
def test_parse_rejects_unsupported_loudly(tmp_path, body, msg):
    with pytest.raises(ConfigError) as e:
        parse_scheduler_config(write(tmp_path, HEADER + body))
    assert msg in str(e.value)


def test_volume_plugins_accepted_as_inert(tmp_path):
    cfg = parse_scheduler_config(write(tmp_path, HEADER + """
profiles:
  - plugins:
      filter:
        disabled:
          - name: VolumeBinding
          - name: VolumeZone
"""))
    assert cfg.disabled_kernel_filters == frozenset()
    assert cfg.disabled_encoder_filters == frozenset()


# ------------------------------------------------------------ engine effects ----


def _sched(nodes, pods, cfg=None):
    sim = Simulator(copy.deepcopy(nodes), sched_config=cfg)
    failed = sim.schedule_pods(copy.deepcopy(pods))
    placements = {}
    for i, nodepods in enumerate(sim.pods_on_node):
        for p in nodepods:
            placements[p["metadata"]["name"]] = i
    return placements, failed


def test_score_weights_change_placement(tmp_path):
    """Two nodes: Simon's bin-packing prefers the small node (min-max gives it
    the full 100 there), LeastAllocated prefers the roomy one. With default
    weights Simon (+ its Open-Gpu-Share twin) dominates; a config file that
    boosts LeastAllocated flips the winner, and so does disabling Simon —
    the file's weights demonstrably govern scoring in both directions."""
    nodes = [
        make_node("roomy", cpu="32", memory="64Gi"),
        make_node("snug", cpu="8", memory="16Gi"),
    ]
    # pre-load snug a bit so least/balanced scores separate
    seed = [make_pod("seed", cpu="2", memory="4Gi", node_name="snug")]
    pod = [make_pod("probe", cpu="2", memory="4Gi")]

    default_place, _ = _sched(nodes, seed + pod)
    assert default_place["probe"] == 1  # Simon's bin-packing wins by default

    boosted = parse_scheduler_config(write(tmp_path, HEADER + """
profiles:
  - plugins:
      score:
        enabled:
          - name: NodeResourcesLeastAllocated
            weight: 10
"""))
    boosted_place, _ = _sched(nodes, seed + pod, boosted)
    assert boosted_place["probe"] == 0  # least-allocated now dominates

    no_simon = parse_scheduler_config(write(tmp_path, HEADER + """
profiles:
  - plugins:
      score:
        disabled:
          - name: Simon
          - name: Open-Gpu-Share
"""))
    no_simon_place, _ = _sched(nodes, seed + pod, no_simon)
    assert no_simon_place["probe"] == 0


def test_filter_disable_taints():
    nodes = [make_node("tainted", taints=[{
        "key": "dedicated", "value": "x", "effect": "NoSchedule"}])]
    pods = [make_pod("p0", cpu="1", memory="1Gi")]
    _, failed = _sched(nodes, pods)
    assert len(failed) == 1  # taint blocks by default
    cfg = SchedulerConfig(disabled_encoder_filters=frozenset({"TaintToleration"}))
    placements, failed = _sched(nodes, pods, cfg)
    assert not failed and placements["p0"] == 0


def test_filter_disable_ports():
    nodes = [make_node("n0")]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi", host_ports=[8080])
            for i in range(2)]
    _, failed = _sched(nodes, pods)
    assert len(failed) == 1  # second pod conflicts on the host port
    cfg = SchedulerConfig(disabled_kernel_filters=frozenset({"NodePorts"}))
    _, failed = _sched(nodes, pods, cfg)
    assert not failed


def test_filter_disable_spread():
    nodes = [make_node(f"n{i}", labels={"zone": "a" if i < 2 else "b"})
             for i in range(3)]
    pods = []
    for i in range(8):
        p = make_pod(f"s{i}", cpu="100m", memory="128Mi", labels={"app": "s"})
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "s"}},
        }]
        pods.append(p)
    nodes[2]["status"]["allocatable"]["cpu"] = "200m"  # zone b nearly full
    nodes[2]["status"]["capacity"]["cpu"] = "200m"
    _, failed = _sched(nodes, pods)
    assert failed  # zone-b capacity caps the whole workload via maxSkew
    cfg = SchedulerConfig(disabled_kernel_filters=frozenset({"PodTopologySpread"}))
    _, failed = _sched(nodes, pods, cfg)
    assert not failed


def test_applier_accepts_scheduler_config_file(tmp_path, monkeypatch):
    import os as _os

    from open_simulator_tpu.apply.applier import Applier, Options

    REPO = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    _os.chdir(REPO)
    sched = write(tmp_path, HEADER + """
profiles:
  - plugins:
      score:
        enabled:
          - name: Simon
            weight: 2
""")
    ap = Applier(Options(simon_config="examples/simon-smoke-config.yaml",
                         default_scheduler_config=sched))
    res = ap.run()
    assert res is not None

    bad = write(tmp_path, HEADER + "extenders:\n  - urlPrefix: http://x\n")
    with pytest.raises(ConfigError):
        Applier(Options(simon_config="examples/simon-smoke-config.yaml",
                        default_scheduler_config=bad))
