"""Fixture for the unclassified-network-error rule (NEVER imported — AST
only). The `live` basename puts this module in the rule's scope. The
findings half catches network errors without routing them to the typed
taxonomy (AuthError / TransientError / ProtocolError); the waived half is
a genuinely non-network OSError with its reason; the clean half routes
every catch — typed raise, bare re-raise, aliased taxonomy import — and a
non-network except stays out of scope."""

import http.client
import socket
import urllib.error
from urllib.error import HTTPError


class AuthError(Exception):
    pass


class TransientError(Exception):
    pass


class ProtocolError(Exception):
    pass


from simulator.live import ProtocolError as ProtoErr  # noqa: E402


# --------------------------------------------------------------- findings ----


def swallowed_read(conn):
    try:
        return conn.read()
    except OSError:  # FINDING: dropped connection becomes a silent None
        return None


def logged_not_routed(url, log):
    try:
        return url.open()
    except urllib.error.URLError as e:  # FINDING: logging is not routing
        log.warning("open failed: %s", e)


def tuple_of_resets(sock):
    try:
        return sock.recv(4096)
    except (socket.timeout, ConnectionResetError):  # FINDING: tuple catch
        pass


def http_exception_continue(resp):
    for _ in range(3):
        try:
            return resp.getheaders()
        except http.client.HTTPException:  # FINDING: retry loop bypasses policy
            continue


def wrong_taxonomy(client):
    try:
        return client.get("/api/v1/nodes")
    except HTTPError as e:  # FINDING: ValueError is not a taxonomy class
        raise ValueError(f"bad response: {e}")


# ------------------------------------------------------------------ waived ----


def read_bookmark(path):
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    # simonlint: ignore[unclassified-network-error] -- local bookmark file
    # read, not a network path: a missing file means a cold start
    except OSError:
        return None


# -------------------------------------------------------------------- clean ----


def routed_transient(sock):
    try:
        return sock.recv(4096)
    except (OSError, http.client.HTTPException) as e:
        raise TransientError(f"recv failed: {e}") from e


def routed_auth(resp):
    try:
        return resp.read()
    except urllib.error.HTTPError as e:
        if e.code in (401, 403):
            raise AuthError(str(e)) from e
        raise TransientError(str(e)) from e


def reraised(conn):
    try:
        return conn.read()
    except ConnectionResetError:
        conn.close()
        raise


def routed_via_alias(conn):
    try:
        return conn.getresponse()
    except OSError as e:
        raise ProtoErr(f"connection in a bad state: {e}") from e


def non_network_is_out_of_scope(blob):
    try:
        return int(blob)
    except (TypeError, ValueError):
        return 0
