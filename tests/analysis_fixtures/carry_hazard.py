"""simonlint fixture: carry-contract hazards. NEVER imported — AST only."""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class GoodCarry(NamedTuple):
    total: jax.Array
    count: jax.Array


class OtherCarry(NamedTuple):
    total: jax.Array


def unannotated(xs):
    def body(carry, x):  # FINDING: carry has no contract annotation
        return carry + x, x

    return jax.lax.scan(body, jnp.float32(0.0), xs)


def tuple_init(xs):
    def body(carry: GoodCarry, x):
        return GoodCarry(carry.total + x, carry.count + 1), x

    # FINDING: bare-tuple init vs declared GoodCarry contract
    return jax.lax.scan(body, (jnp.float32(0.0), jnp.int32(0)), xs)


def branch_drift(xs):
    def body(carry: GoodCarry, x):
        if True:  # pragma: no cover - fixture
            return OtherCarry(carry.total + x), x  # FINDING: wrong contract
        return GoodCarry(carry.total, carry.count), x

    return jax.lax.scan(body, GoodCarry(jnp.float32(0.0), jnp.int32(0)), xs)


def arity_drift(xs):
    def body(carry: GoodCarry, x):
        return GoodCarry(carry.total + x), x  # FINDING: 1 leaf vs 2 fields

    return jax.lax.scan(body, GoodCarry(jnp.float32(0.0), jnp.int32(0)), xs)


def lambda_body(xs):
    # FINDING: unresolvable body
    return jax.lax.scan(lambda c, x: (c + x, x), jnp.float32(0.0), xs)


def clean(xs):
    def body(carry: GoodCarry, x):
        nxt = GoodCarry(carry.total + x, carry.count + 1)
        return nxt, carry.total

    return jax.lax.scan(body, GoodCarry(jnp.float32(0.0), jnp.int32(0)), xs)
