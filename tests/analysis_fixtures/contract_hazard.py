"""simonlint fixture: contract-spec hazards. NEVER imported — AST only."""

from open_simulator_tpu.ops.contracts import shaped


@shaped(vec="[N] f32", ret="[N] f32")
def clean_kernel(vec):
    return vec


@shaped(nope="[N] f32")  # FINDING: 'nope' is not a parameter
def wrong_name(vec):
    return vec


@shaped(vec="[N] q99")  # FINDING: unknown dtype token
def wrong_dtype(vec):
    return vec


@shaped(vec="N] f32")  # FINDING: unparseable spec
def wrong_grammar(vec):
    return vec
