"""simonlint fixture: a module with no findings (negative control)."""

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class RunningSum(NamedTuple):
    acc: jax.Array


@partial(jax.jit, static_argnames=("scale",))
def scaled_sum(xs, scale: int = 1):
    def body(carry: RunningSum, x):
        return RunningSum(carry.acc + x * scale), x

    final, _ = jax.lax.scan(body, RunningSum(jnp.float32(0.0)), xs)
    return final.acc
