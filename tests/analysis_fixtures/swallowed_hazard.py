"""simonlint fixture: swallowed-exception hazards. NEVER imported — AST only."""

import logging
import sys

log = logging.getLogger(__name__)


def swallow_pass():
    try:
        risky()  # noqa: F821 - fixture
    except Exception:  # FINDING: the classic silent swallow
        pass


def swallow_bare():
    try:
        risky()  # noqa: F821 - fixture
    except:  # noqa: E722 - fixture  # FINDING: bare except, fallback only
        value = None
    return value


def swallow_tuple():
    try:
        risky()  # noqa: F821 - fixture
    except (ValueError, Exception):  # FINDING: Exception hides in the tuple
        value = 0
    return value


def swallow_waived():
    try:
        risky()  # noqa: F821 - fixture
    except Exception:  # simonlint: ignore[swallowed-exception] -- best-effort cleanup, fixture
        pass


def ok_narrow():
    try:
        risky()  # noqa: F821 - fixture
    except ValueError:  # narrow type: a typed decision, not a swallow
        value = 0
    return value


def ok_reraise():
    try:
        risky()  # noqa: F821 - fixture
    except Exception as e:
        raise RuntimeError("wrapped") from e


def ok_logged():
    try:
        risky()  # noqa: F821 - fixture
    except Exception as e:
        log.warning("risky failed: %s", e)


def ok_counted(metric):
    try:
        risky()  # noqa: F821 - fixture
    except Exception:
        metric.labels(reason="boom").inc()


def ok_returns_error():
    try:
        risky()  # noqa: F821 - fixture
    except Exception as e:
        return 500, str(e)
    return 200, "ok"


def ok_exits():
    try:
        risky()  # noqa: F821 - fixture
    except Exception as e:
        print(f"fatal: {e}", file=sys.stderr)
        sys.exit(1)
