"""Fixture for the naked-dispatch rule: direct kernel dispatches that bypass
guard.supervised must fire; supervised forms (lambda, functools.partial,
named function / method argument) and suppressed sites must not."""

import functools

from open_simulator_tpu.ops import kernels
from open_simulator_tpu.resilience import guard

tables = carry = active = pg = fn = vd = None


def naked_serial():
    # finding: direct dispatch, no watchdog
    return kernels.schedule_batch(tables, carry, pg, fn, vd)


def naked_wave():
    # finding: direct dispatch in an assignment
    c, counts, placed = kernels.schedule_wave(tables, carry, 0, 8, False)
    return counts


def naked_affinity_wave():
    # finding: the epoch-batched affinity wave blocks at fetch just the same
    c, counts, placed = kernels.schedule_affinity_wave(tables, carry, 0, 8, False)
    return counts


def naked_affinity_fanout():
    # finding: fan-out variant of the affinity wave, also unsupervised
    return kernels.probe_affinity_wave_fanout(tables, carry, active, 0, 8, False)


def naked_feasibility():
    # finding: feasibility dispatch blocks at fetch just the same
    feasible, stages = kernels.feasibility_jit(tables, carry, 0, -1, True)
    return feasible


def naked_suppressed():
    # simonlint: ignore[naked-dispatch] -- offline harness, no wedge exposure
    return kernels.probe_serial_fanout(tables, carry, active, pg, fn, vd)


def guarded_lambda():
    return guard.supervised(
        lambda: kernels.schedule_batch(tables, carry, pg, fn, vd),
        site="dispatch", pods=8)


def guarded_partial():
    call = functools.partial(kernels.schedule_group_serial, tables, carry)
    return guard.supervised(call, site="dispatch", pods=8)


def _round():
    return kernels.probe_wave_fanout(tables, carry, active, 0, 8, False)


def guarded_named_function():
    return guard.supervised(_round, site="dispatch", pods=8)


class Session:
    def _dispatch_round(self, active_s):
        return kernels.probe_group_serial_fanout(tables, carry, active_s)

    def dispatch(self, active_s):
        return guard.supervised(
            functools.partial(self._dispatch_round, active_s),
            site="dispatch", pods=8)
