"""simonlint fixture: metric-in-jit hazards. NEVER imported — analyzed as AST only."""

import time

import jax
import jax.numpy as jnp
from functools import partial

from open_simulator_tpu.obs.metrics import counter, histogram

STEPS = counter("fixture_steps_total", "scan steps")
LATENCY = histogram("fixture_latency_seconds", "latencies")


@jax.jit
def counts_per_compile(x):
    STEPS.inc()  # FINDING: registry mutation under trace (runs once, at trace time)
    return x + 1


@jax.jit
def bakes_a_timestamp(x):
    t0 = time.perf_counter()  # FINDING: wall-clock read under trace
    return x * t0


@partial(jax.jit, static_argnames=("debug",))
def observes_under_trace(x, debug):
    y = jnp.sum(x)
    LATENCY.observe(0.0)  # FINDING: histogram mutation under trace
    return y


@jax.jit
def builds_metric_under_trace(x):
    import open_simulator_tpu.obs.metrics as m

    c = m.counter("fixture_inner_total", "constructed mid-trace")  # FINDING
    return x


def scan_user(xs):
    def body(carry, x):
        STEPS.inc()  # FINDING: mutation inside scan body
        return carry + x, x

    return jax.lax.scan(body, jnp.float32(0.0), xs)


@jax.jit
def at_set_is_fine(x):
    # .set() via the functional-update idiom must NOT fire (the reason the
    # rule's mutator list excludes bare .set)
    return x.at[0].set(1.0)


@jax.jit
def suppressed_inc(x):
    STEPS.inc()  # simonlint: ignore[metric-in-jit] -- fixture: tests suppression
    return x


def host_side_is_fine(x):
    # not traced: dispatch-site instrumentation is exactly where this belongs
    t0 = time.perf_counter()
    out = at_set_is_fine(x)
    LATENCY.observe(time.perf_counter() - t0)
    STEPS.inc()
    return out
