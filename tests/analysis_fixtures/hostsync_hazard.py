"""simonlint fixture: host-sync-in-jit hazards. NEVER imported — analyzed as AST only."""

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@jax.jit
def pulls_scalar(x):
    total = jnp.sum(x)
    return total.item()  # FINDING: .item() on traced value


@partial(jax.jit, static_argnames=("flag",))
def mixed(x, flag):
    y = x * 2.0
    host = np.asarray(y)  # FINDING: np.asarray on traced value
    if flag:  # static: fine
        print(y)  # FINDING: print on traced value
    return host


@jax.jit
def casts(x):
    n = float(x)  # FINDING: float() on traced value
    return n


@jax.jit
def suppressed_pull(x):
    return x.item()  # simonlint: ignore[host-sync-in-jit] -- fixture: tests suppression


def scan_user(xs):
    def body(carry, x):
        v = carry + x
        np.array(v)  # FINDING: host sync inside scan body
        return v, v

    return jax.lax.scan(body, jnp.float32(0.0), xs)


def host_side_is_fine(x):
    # not traced: no findings here
    arr = np.asarray(x)
    return float(arr.sum())
