"""Fixture for the unbounded-queue rule: every stdlib spelling of an
unbounded producer/consumer channel must fire (Queue with no maxsize, an
explicit maxsize=0, SimpleQueue, bare deque); the waived half is a
deliberately unbounded channel with its bounded-elsewhere argument; the
clean half passes real bounds every way the ctors accept them."""

import collections
import queue
from collections import deque
from queue import Queue

# --------------------------------------------------------------- findings ----

work = queue.Queue()  # no maxsize: unbounded backlog
undo = queue.LifoQueue()
ranked = queue.PriorityQueue(maxsize=0)  # explicit 0 IS unbounded
fast = queue.SimpleQueue()  # unboundable by construction
events = Queue()  # bare-name import, same hazard
ring = deque()
tail = collections.deque([1, 2, 3])

# ------------------------------------------------------------------ waived ----

# simonlint: ignore[unbounded-queue] -- depth bounded by the admission
# controller upstream: at most max_queue items are ever enqueued
overflow = queue.Queue()

# -------------------------------------------------------------------- clean ----

bounded = queue.Queue(maxsize=128)
bounded_pos = queue.Queue(64)
bounded_lifo = queue.LifoQueue(maxsize=8)
recent = deque(maxlen=32)
recent_kw = collections.deque([1, 2], maxlen=2)
recent_pos = deque([1, 2], 2)  # second positional IS the maxlen
