"""unsharded-transfer fixtures: layout-less transfers in a mesh-aware module.

The `from ...parallel.mesh import` below is what makes this module
"mesh-aware" — the rule only patrols modules that touch the sharding
machinery (engine.py, probe.py, parallel/), so kernels.py's single-device
module-level jits stay exempt.
"""

import jax

from open_simulator_tpu.ops import kernels
from open_simulator_tpu.parallel.mesh import table_shardings


def bad_device_put(x):
    return jax.device_put(x)  # FINDING: no explicit sharding


def bad_jit_dispatch():
    # FINDING: a dispatch kernel jitted without in_shardings — GSPMD
    # re-infers the layout per call
    return jax.jit(kernels.schedule_wave, static_argnames=("block",))


def ok_device_put(x, mesh):
    return jax.device_put(x, table_shardings(mesh).alloc)


def ok_jit_with_shardings(mesh):
    ts = table_shardings(mesh)
    return jax.jit(kernels.feasibility_jit, in_shardings=(ts,))


def ok_non_dispatch_jit(fn):
    return jax.jit(fn)  # not a dispatch kernel: out of scope
