"""simonlint fixture: recompile-trigger hazards. NEVER imported — AST only."""

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def scalar_config(x, n_buckets: int, debug: bool = False):
    # FINDING x2: n_buckets and debug look static but are traced
    return jnp.reshape(x, (n_buckets, -1))


@partial(jax.jit, static_argnames=("n_buckets", "debug"))
def scalar_config_ok(x, n_buckets: int, debug: bool = False):
    # clean: both declared static
    return jnp.reshape(x, (n_buckets, -1))


@jax.jit
def tuple_default(x, shape=(8, 8)):
    # FINDING: tuple default not declared static
    return jnp.broadcast_to(x, shape)


def _impl(x, mode: str):
    return x


def _impl_ok(x, mode: str):
    return x


jitted_impl = jax.jit(_impl)  # FINDING on `mode`: call-form jit, str param not static
jitted_impl_ok = jax.jit(_impl_ok, static_argnums=(1,))  # clean
