"""Fixture for the collective-in-scan-body rule: a cross-shard collective
inside a scan/while/fori body — directly or through a locally defined helper
— must fire (one cross-device launch per iteration, the pattern that kept
the sharded hard-predicate wave at 0.1x of serial); collectives hoisted to
the loop boundary, collectives outside any loop, and the waived
epoch-amortized form must not."""

import jax
import jax.numpy as jnp
from jax import lax

state = xs0 = None
AX = "nodes"


def per_round_reduce(carry):
    # findings x2: called FROM the loop body, so transitively per-iteration
    hi = lax.pmax(carry, AX)
    lo = -lax.pmax(-carry, AX)
    return hi + lo


def round_body(c):
    j, acc = c
    acc = acc + per_round_reduce(acc)
    # finding: gather directly in the while body — per-round payload traffic
    rows = jax.lax.all_gather(acc, AX, axis=0, tiled=True)
    return (j + 1, rows.sum())


def hard_wave_rounds(n):
    # the old per-round shape: one reduce + one gather per candidate ROUND
    return lax.while_loop(lambda c: c[0] < n, round_body, (0, state))


def scan_body(c, x):  # simonlint: ignore[carry-contract] -- scalar toy carry, this fixture exercises the collective rule
    # finding: per-step psum in a lax.scan body
    return c + jax.lax.psum(x, AX), x


def scan_reduce(xs):
    return lax.scan(scan_body, 0.0, xs)


def fori_body(i, c):
    # finding: per-step pmean in a fori_loop body
    return c + lax.pmean(c, AX)


def fori_reduce(n):
    return lax.fori_loop(0, n, fori_body, 0.0)


def ok_hoisted_stacked_reduce(n):
    # clean: stack the operands and reduce ONCE before entering the loop —
    # max-space packing covers the mins (-max(-x) == min(x) exactly in f32)
    stacked = jnp.stack([state, -state])
    red = lax.pmax(stacked, AX)
    return lax.while_loop(lambda c: c[0] < n,
                          lambda c: (c[0] + 1, c[1] + red.sum()), (0, 0.0))


def ok_collective_outside_any_loop():
    # clean: a top-level collective is the normal SPMD idiom
    return jax.lax.all_gather(state, AX, axis=0, tiled=True)


def ok_helper_not_called_from_loop(v):
    # clean: the helper reduces, but no scan/while/fori body reaches it
    return per_epoch_summary(v)


def per_epoch_summary(v):
    return lax.psum(v, AX)


def epoch_body_waived(c):
    # the deliberate epoch-amortized form: ONE stacked reduce per epoch IS
    # the fix for the per-round pattern above; waived with a reason
    red = lax.pmax(c[1], AX)  # simonlint: ignore[collective-in-scan-body] -- one stacked reduce per epoch is the amortized design
    return (c[0] + 1, red)


def epoch_loop(n):
    return lax.while_loop(lambda c: c[0] < n, epoch_body_waived, (0, state))
