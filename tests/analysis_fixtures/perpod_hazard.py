"""Fixture: per-pod-host-loop — O(pods) Python loops in a store-adopted module.

This module "adopts" the columnar store (the import below is the structural
applicability marker), then runs per-pod Python loops over a pod batch —
the exact host-path shape the struct-of-arrays rewrite removed from the
engine. Expected findings: 3 (the waived one suppressed, the helpers clean).
"""

import numpy as np

from open_simulator_tpu.simulator import store  # noqa: F401  (adoption marker)


def encode_slow(encoder, pods):
    out = []
    for pod in pods:  # finding 1: per-pod encode traversal
        out.append(encoder.group_of(pod))
    return out


def commit_slow(sim, to_schedule, choices):
    for i, pod in enumerate(to_schedule):  # finding 2: per-pod commit loop
        if choices[i] >= 0:
            sim._commit_pod(pod, int(choices[i]))


def track_slow(batch):
    total = 0
    for gi, fn in batch:  # finding 3: batch re-walk
        total += gi + fn
    return total


def deliberate_fallback(sim, pods):
    for pod in pods:  # simonlint: ignore[per-pod-host-loop] -- gpu ledger writes per-pod annotations; columnar batches ride the bulk path
        sim.gpu_host.reserve(pod, 0)


def vectorized_ok(store_view):
    # the columnar form: one gather, no per-pod Python
    rows = store_view.tmpl_rows()
    return np.bincount(rows)


def unrelated_loop_ok(nodes):
    for n in nodes:  # node axis, not the pod batch
        n.get("metadata")
