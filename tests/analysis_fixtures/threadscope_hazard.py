"""Fixture: jax config scopes entered in one thread, work submitted to
another inside them (config-scope-across-thread). The hazard half submits
from inside the scope; the ok half re-enters the scope in the worker (the
guard.supervised pattern) or submits outside the scope."""

import threading
from concurrent.futures import ThreadPoolExecutor

import jax


def dispatch(x):
    return x


def hazard_submit_in_default_device(pool: ThreadPoolExecutor, x):
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        return pool.submit(dispatch, x)  # scope dropped in the worker


def hazard_thread_in_disable_jit(x):
    with jax.disable_jit():
        t = threading.Thread(target=dispatch, args=(x,))
        t.start()
    return t


def hazard_timer_in_matmul_precision(x):
    with jax.default_matmul_precision("float32"):
        threading.Timer(0.1, dispatch, args=(x,)).start()


def hazard_run_in_executor(loop, x):
    with jax.transfer_guard("disallow"):
        return loop.run_in_executor(None, dispatch, x)


def suppressed_submit(pool: ThreadPoolExecutor, x):
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        # simonlint: ignore[config-scope-across-thread] -- fixture: the task provably never touches jax
        return pool.submit(dispatch, x)


def ok_reenter_scope_in_worker(pool: ThreadPoolExecutor, x):
    cpu = jax.devices("cpu")[0]

    def task():
        with jax.default_device(cpu):  # the fix: scope re-entered in-thread
            return dispatch(x)

    return pool.submit(task)


def ok_submit_outside_scope(pool: ThreadPoolExecutor, x):
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        y = dispatch(x)
    return pool.submit(dispatch, y)


def ok_plain_with_block(lock, pool: ThreadPoolExecutor, x):
    with lock:  # not a jax config scope
        return pool.submit(dispatch, x)
