"""Fixture for the entropy-into-report rule: ambient entropy (wall clocks,
unseeded random, set iteration order) flowing into json.dump/json.dumps must
fire — including through ONE level of module-local helper call (`_now_ms`).
The waived half is a bench-style record whose wall timings ARE the payload;
the clean half shows sorted-set, seeded-rng, and pid-suffixed-tmp-path forms
that must stay quiet."""

import json
import os
import random
import time


# ---------------------------------------------------------------- findings ----


def stamped_report(rows):
    doc = {"rows": rows, "generated_at": time.time()}
    return json.dumps(doc, sort_keys=True)  # finding: wall clock in report


def _now_ms():
    return int(time.time() * 1000)


def helper_stamped(path, rows):
    stamp = _now_ms()  # one call level deep: the helper summary carries it
    with open(path, "w") as f:
        json.dump({"rows": rows, "at": stamp}, f)  # finding


def jittered_pick(rows):
    pick = random.choice(rows)  # unseeded module-level random
    return json.dumps({"pick": pick})  # finding


def set_order_leak(names):
    seen = set(names)
    out = []
    for n in seen:  # set iteration order is hash-seed-dependent
        out.append(n)
    return json.dumps(out)  # finding


# ------------------------------------------------------------------ waived ----


def bench_record(rows, elapsed_s):
    # simonlint: ignore[entropy-into-report] -- bench record: wall timings
    # ARE the payload (BENCH_ANALYSIS-style artifact, not a golden)
    return json.dumps({"rows": rows, "recorded_unix": time.time(),
                       "elapsed_s": elapsed_s})


# ------------------------------------------------------------------- clean ----


def sorted_set_is_deterministic(names):
    return json.dumps(sorted(set(names)))  # clean: sorted() fixes the order


def seeded_rng_is_deterministic(rows, seed):
    rng = random.Random(seed)
    pick = rng.choice(rows)  # clean: seeded instance, not module-level
    return json.dumps({"pick": pick})


def pid_tmp_path_is_content_clean(rec, path):
    tmp = f"{path}.tmp.{os.getpid()}"  # clean: entropy names the FILE,
    with open(tmp, "w") as f:          # not the record
        json.dump(rec, f)
    os.replace(tmp, path)


def pure_payload(rows):
    return json.dumps({"rows": rows}, sort_keys=True)  # clean
