"""Fixture for the unattributed-dispatch rule: a hot-kernel dispatch under
guard.supervised whose attribution path has no obs.record_dispatch must fire
(the dispatch is invisible to the compile-cache census and lands in the
simonpulse ledger with no kernel/bucket attribution); the engine pattern
(record_dispatch at the call site), the probe pattern (record_dispatch
inside the wrapped body), supervised host work with no kernel dispatch, and
suppressed sites must not.

Every supervised() call here also carries a naked-dispatch-free form on
purpose — this rule's beat starts where naked-dispatch's ends (the dispatch
IS supervised; what's missing is the ledger note)."""

import functools

from open_simulator_tpu import obs
from open_simulator_tpu.ops import kernels
from open_simulator_tpu.resilience import guard

tables = carry = active = pg = fn = vd = None


def unattributed_lambda():
    # finding: supervised, but no record_dispatch anywhere on the path
    return guard.supervised(
        lambda: kernels.schedule_batch(tables, carry, pg, fn, vd),
        site="dispatch", pods=8)


def unattributed_partial():
    # finding: partial resolution matches guard.supervised's, still no note
    call = functools.partial(kernels.schedule_group_serial, tables, carry)
    return guard.supervised(call, site="dispatch", pods=8)


def _bare_round():
    return kernels.probe_wave_fanout(tables, carry, active, 0, 8, False)


def unattributed_named_function():
    # finding: the wrapped body dispatches and neither scope has the note
    return guard.supervised(_bare_round, site="dispatch", pods=8)


def attributed_call_site():
    # clean (engine pattern): record_dispatch at the supervised call site
    obs.record_dispatch("schedule_batch", P=8, N=4)
    return guard.supervised(
        lambda: kernels.schedule_batch(tables, carry, pg, fn, vd),
        site="dispatch", pods=8)


def _noted_round():
    # clean (probe pattern): the note is parked from inside the worker, so
    # it crosses into the watchdog thread with the copied context
    obs.record_dispatch("probe_wave_fanout", K=8, N=4)
    return kernels.probe_wave_fanout(tables, carry, active, 0, 8, False)


def attributed_wrapped_body():
    return guard.supervised(_noted_round, site="dispatch", pods=8)


def supervised_fetch_is_fine():
    # clean: supervised host work (a fetch) dispatches no kernel — there is
    # nothing to attribute
    import numpy as np

    return guard.supervised(lambda: np.asarray(carry), site="fetch", pods=8)


def suppressed_unattributed():
    # simonlint: ignore[unattributed-dispatch] -- offline harness, ledger attribution not needed
    return guard.supervised(
        lambda: kernels.schedule_wave(tables, carry, 0, 8, False),
        site="dispatch", pods=8)
