"""Fixture for the span-outside-guard rule: a tracing Span opened around a
kernel dispatch that bypasses guard.supervised must fire (the span would
record wall time the watchdog can abandon); the supervised-inside-span form,
span-free dispatches (naked-dispatch's beat, not this rule's), and
suppressed sites must not."""

import functools

from open_simulator_tpu.ops import kernels
from open_simulator_tpu.resilience import guard
from open_simulator_tpu.utils.trace import Span

tables = carry = active = pg = fn = vd = sc = None


def span_around_naked_dispatch():
    # finding: the span measures a dispatch the watchdog cannot contain
    with Span("dispatch"):
        return kernels.schedule_batch(tables, carry, pg, fn, vd)


def scope_span_around_naked_dispatch():
    # finding: simonscope live spans are the same hazard
    with sc.span("kernel:wave"):
        c, counts, placed = kernels.schedule_wave(tables, carry, 0, 8, False)
    return counts


def span_with_step_around_fanout():
    # finding: nested statements inside the with-body are still covered
    with Span("probe") as span:
        span.step("setup")
        out = kernels.probe_serial_fanout(tables, carry, active, pg, fn, vd)
    return out


def span_around_supervised_is_fine():
    # clean: the span may time the SUPERVISED call — the watchdog contains
    # the dispatch, the span just reads the wall clock around it
    with Span("dispatch"):
        return guard.supervised(
            lambda: kernels.schedule_batch(tables, carry, pg, fn, vd),
            site="dispatch", pods=8)


def span_around_supervised_partial_is_fine():
    # clean: functools.partial resolution matches guard.supervised's
    with sc.span("kernel:serial"):
        call = functools.partial(kernels.schedule_group_serial, tables, carry)
        return guard.supervised(call, site="dispatch", pods=8)


def span_without_dispatch_is_fine():
    # clean: spans around host work are the normal case
    with Span("encode"):
        return [tables, carry]


def suppressed_span_dispatch():
    with Span("offline"):
        # simonlint: ignore[span-outside-guard, naked-dispatch] -- offline audit harness, no wedge exposure
        return kernels.probe_wave_fanout(tables, carry, active, 0, 8, False)
