"""Fixture: suppression waivers without their `-- reason` text
(suppression-reason). The bare waiver still suppresses its target rule, but
is itself a WARNING finding; the reasoned forms are clean."""

import numpy as np


def bare_trailing_waiver():
    return np.zeros(4, np.float64)  # simonlint: ignore[dtype-drift]


def bare_comment_only_waiver():
    # simonlint: ignore[dtype-drift]
    return np.ones(4, np.float64)


def reasoned_waiver_is_clean():
    return np.zeros(4, np.float64)  # simonlint: ignore[dtype-drift] -- fixture: host staging buffer


def reasoned_comment_only_is_clean():
    # simonlint: ignore[dtype-drift] -- fixture: host staging buffer
    return np.ones(4, np.float64)
