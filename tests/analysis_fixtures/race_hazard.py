"""Fixture for the race-unguarded-attr rule: attributes consistently written
under a lock must not be touched off-lock in multi-thread-reachable classes.
The findings half includes a reconstruction of the PR 14 pre-fix torn-scrape
bug (off-lock samples() read of lock-guarded child state) — the known-bug
regression the pass exists to catch. The waived half shows a deliberate racy
fast path with its happens-before argument; the clean half shows the locked,
`*_locked`-convention, and never-escaping forms that must stay quiet."""

import threading

_STATE_LOCK = threading.Lock()
_EVENTS = []


# ------------------------------------------------- findings: torn scrape ----


class TornScrapeFamily:
    """PR 14 pre-fix shape: children mutate under the family lock, samples()
    reads them bucket-by-bucket OFF-lock — rows whose sum/count never
    co-occurred."""

    def __init__(self):
        self._lock = threading.Lock()
        self._children = {}

    def labels(self, key):
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = TornScrapeChild(self)
                self._children[key] = child
        return child

    def samples(self):
        out = []
        # finding: _children read off-lock (guarded write in labels)
        for child in list(self._children.values()):
            # findings: _count/_sum are written under the family lock in
            # TornScrapeChild.observe but read here with no lock held
            out.append((child._count, child._sum))
        return out


class TornScrapeChild:
    def __init__(self, family: "TornScrapeFamily"):
        self._family = family
        self._count = 0
        self._sum = 0.0

    def observe(self, v):
        with self._family._lock:
            self._count += 1
            self._sum += v


# ------------------------------------- findings: escape + module globals ----


class EscapingWorker:
    """No lock of its own, but a bound method escapes to a Thread — the
    class is multi-thread-reachable, so off-lock reads of its guarded state
    are findings."""

    def __init__(self):
        self.items = []
        self.t = None

    def start(self):
        self.t = threading.Thread(target=self._run, name="fixture-worker",
                                  daemon=True)
        self.t.start()

    def _run(self):
        with _STATE_LOCK:
            self.items.append(1)

    def snapshot(self):
        return len(self.items)  # finding: off-lock read, class escapes


def record_event(evt):
    with _STATE_LOCK:
        _EVENTS.append(evt)


def peek_events():
    return list(_EVENTS)  # finding: module global guarded by _STATE_LOCK


# ------------------------------------------------------------------ waived ----


class RacyGauge:
    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self):
        with self._lock:
            self._value += 1

    def peek(self):
        # simonlint: ignore[race-unguarded-attr] -- monitoring read: int load
        # is GIL-atomic and the gauge tolerates one-increment staleness
        return self._value


# ------------------------------------------------------------------- clean ----


class LockedCounter:
    """Clean: every access takes the lock, and the `*_locked` suffix marks
    the caller-holds-lock contract."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def inc(self):
        with self._lock:
            self._n += 1
            self._reset_if_huge_locked()

    def value(self):
        with self._lock:
            return self._n

    def _reset_if_huge_locked(self):
        if self._n > 1 << 30:
            self._n = 0


class Unshared:
    """Clean: owns no lock and never escapes to a thread — not patrolled."""

    def __init__(self):
        self.hits = 0

    def bump(self):
        self.hits += 1
