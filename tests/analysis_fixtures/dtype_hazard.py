"""simonlint fixture: dtype-drift hazards. NEVER imported — AST only."""

import jax.numpy as jnp
import numpy as np


def widen(rows):
    staged = np.zeros((4, 4), np.float64)  # FINDING: attribute float64
    ids = np.arange(10, dtype="int64")  # FINDING: string dtype
    dev = jnp.asarray(staged)  # the silent downcast the rule exists for
    return dev, ids


def whitelisted(rows):
    acc = np.zeros(8, np.float64)  # simonlint: ignore[dtype-drift] -- fixture: host accumulator
    return acc


def device_wide(x):
    return jnp.zeros_like(x, dtype=jnp.int64)  # FINDING: jnp int64
