"""Fixture for the lock-order-cycle rule: the crafted 3-lock cycle
(A->B in one path, B->C in another, C->A in a third) must fire even though
no single function holds all three; the waived half is a 2-lock inversion
with its cannot-run-concurrently argument; the clean half acquires in one
consistent order, including through a call made under the outer lock."""

import threading

_ALLOC = threading.Lock()
_BILL = threading.Lock()
_COMMIT = threading.Lock()


# ------------------------------------------ findings: 3-lock cycle A->B->C->A


def alloc_then_bill():
    with _ALLOC:
        with _BILL:
            pass


def bill_then_commit():
    with _BILL:
        with _COMMIT:
            pass


def commit_then_alloc():
    with _COMMIT:
        with _ALLOC:
            pass


# ------------------------------------------------------------------ waived ----

_DRAIN = threading.Lock()
_EXPORT = threading.Lock()


def drain_then_export():
    with _DRAIN:
        # simonlint: ignore[lock-order-cycle] -- phase-exclusive: drain runs
        # only after the exporter thread has been joined, so the inverted
        # export->drain path can never interleave with this one
        with _EXPORT:
            pass


def export_then_drain():
    with _EXPORT:
        with _DRAIN:
            pass


# ------------------------------------------------------------------- clean ----

_OUTER = threading.Lock()
_INNER = threading.Lock()


def outer_then_inner():
    with _OUTER:
        with _INNER:
            pass


def outer_then_inner_via_call():
    # clean: the call-under-lock edge (_OUTER -> _INNER through the helper
    # summary) agrees with the direct nesting above — same order, no cycle
    with _OUTER:
        _flush_inner()


def _flush_inner():
    with _INNER:
        pass


def reentrant_is_not_an_order_fact():
    # clean: A-while-A is RLock re-entry territory, not an order inversion
    with _OUTER:
        with _OUTER:
            pass
