"""Fixture: device->host fetches inside per-segment/epoch/round loop bodies
(fetch-in-wave-loop). The bad_* half pays one device round trip per loop
iteration; the ok_* half collects device values and fetches once after the
loop (the designated spill point), or carries an explicit waiver.

Expected findings: 3 (two in bad_epoch_poll, one in bad_per_segment_fetch).
"""

import jax
import numpy as np


def bad_per_segment_fetch(segs, outs):
    total = 0
    for seg in segs:  # the engine-style per-segment dispatch loop
        total += int(np.asarray(outs[seg]).sum())  # fetch per iteration
    return total


def bad_epoch_poll(n_epochs, x):
    y = None
    for epoch in range(n_epochs):
        jax.block_until_ready(x)  # blocks the pipeline every epoch
        y = jax.device_get(x)     # and fetches it again
    return y


def ok_post_loop_spill(segs, outs):
    acc = []
    for seg in segs:
        acc.append(outs[seg])     # device refs only; no sync in the loop
    return np.asarray(acc)        # ONE fetch at the spill point


def ok_waived_blocking_probe(segs, outs):
    for seg in segs:
        # simonlint: ignore[fetch-in-wave-loop] -- deliberate per-segment timing probe
        np.asarray(outs[seg])


def ok_plain_loop(items, outs):
    for item in items:            # not a segment/epoch/round loop
        np.asarray(outs[item])
