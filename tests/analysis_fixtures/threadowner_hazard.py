"""Fixture for the thread-owner rule: every started Thread/Timer must be
daemon-with-a-name (the attribution convention `simon top` and stack dumps
rely on) or joined somewhere in the module. The waived half names its owner;
the clean half shows the named-daemon, joined-local, and joined-attribute
forms that must stay quiet."""

import threading


# ---------------------------------------------------------------- findings ----


def anon_daemon_worker(fn):
    # finding: daemon but anonymous — nothing can attribute or find it
    threading.Thread(target=fn, daemon=True).start()


def named_but_unowned(fn):
    # finding: named yet neither daemon nor joined in this module
    loose = threading.Thread(target=fn, name="fixture-loose")
    loose.start()


def anon_timer(fn):
    # finding: Timers are threads too
    threading.Timer(0.1, fn).start()


# ------------------------------------------------------------------ waived ----


def one_shot_cli_worker(fn):
    # simonlint: ignore[thread-owner] -- owner: the CLI one-shot path;
    # process exit reaps it before any shutdown path exists
    threading.Thread(target=fn).start()


# ------------------------------------------------------------------- clean ----


def named_daemon(fn):
    threading.Thread(target=fn, name="fixture-owned", daemon=True).start()


def joined_local(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()


class OwnedSampler:
    """Clean: the constructed thread is an attribute joined on a named
    shutdown path (the obs.scope RuntimeSampler shape)."""

    def __init__(self, fn):
        self._thread = threading.Thread(target=fn)

    def start(self):
        self._thread.start()

    def stop(self):
        self._thread.join()
