"""simonpulse tests: the per-dispatch performance ledger (obs/pulse.py).

The contract under test (ISSUE 18 acceptance):
- the ring is bounded: records past capacity evict the oldest and count the
  eviction (ledger drops are observable, never silent);
- ledger dispatch records reconcile EXACTLY with the
  simon_compile_cache_{hits,misses}_total census and run-record pods with
  simon_scheduling_attempts_total on a real Simulator run (record_dispatch
  is the single definition of "one dispatch happened");
- records are keyed by the simonaudit digest family: same (kernel, dims) →
  same 16-hex digest == analysis.hlo.dispatch_digest; a forced recompile
  (new shape bucket) shows up as a NEW digest with a cold record;
- pulse off is bit-identical: same placements/reasons, zero movement in any
  simon_pulse_* metric;
- an injected slow warm dispatch trips the MAD drift detector against the
  PRIOR window (the outlier cannot raise its own baseline);
- the static roofline covers every HOT_KERNELS entry at both audit buckets
  on 1/2/8-shard meshes (cost fields in the audit goldens);
- the JSONL spill rotates at the size cap and round-trips through
  summarize_records (the `simon pulse --jsonl` path).
"""

import copy
import json
import re

import pytest

from open_simulator_tpu.analysis.hlo import dispatch_digest
from open_simulator_tpu.obs import REGISTRY, instruments, pulse
from open_simulator_tpu.ops import kernels
from open_simulator_tpu.resilience import guard
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.utils.synth import synth_cluster


@pytest.fixture(autouse=True)
def _clean_pulse_and_guard():
    pulse.reset_for_tests()
    guard.reset_for_tests()
    yield
    pulse.reset_for_tests()
    guard.reset_for_tests()


def _vals():
    return REGISTRY.values()


def _sum(values, prefix):
    return sum(v for k, v in values.items() if k.startswith(prefix))


def _pulse_deltas(v0, v1):
    keys = {k for k in v0 if k.startswith("simon_pulse_")} | {
        k for k in v1 if k.startswith("simon_pulse_")}
    return {k: v1.get(k, 0) - v0.get(k, 0) for k in keys
            if v1.get(k, 0) != v0.get(k, 0)}


def _commit(p, kernel="schedule_wave", dims=None, cold=False,
            wall_s=1e-3, site="dispatch", pods=4):
    """One synthetic attributed dispatch: park a note the way
    obs.record_dispatch's hook does, then drain it the way guard.supervised
    does after the unit returns."""
    pulse.note_dispatch(kernel, dims if dims is not None else
                        {"N": 8, "P": 4}, cold)
    p.commit_unit(site=site, pods=pods, wall_s=wall_s)


def run_once(nodes, pods):
    sim = Simulator(copy.deepcopy(nodes))
    failed = sim.schedule_pods(copy.deepcopy(pods))
    placements = {}
    for i, node_pods in enumerate(sim.pods_on_node):
        for p in node_pods:
            placements[p["metadata"]["name"]] = i
    reasons = {u.pod["metadata"]["name"]: u.reason for u in failed}
    return placements, reasons


@pytest.fixture(scope="module")
def small_cluster():
    return synth_cluster(16, 60, hard_predicates=True)


# ------------------------------------------------------------- ring bounds ---


def test_ring_bounds_and_drop_accounting():
    v0 = _vals()
    p = pulse.enable(capacity=4)
    assert instruments._DISPATCH_HOOK is pulse.note_dispatch
    for i in range(7):
        _commit(p, wall_s=1e-3 * (i + 1))
    recs = p.records()
    assert len(recs) == 4
    # the ring keeps the NEWEST records; seq is monotone
    assert [r["seq"] for r in recs] == [4, 5, 6, 7]
    s = p.summary()
    assert s["records_total"] == 7
    assert s["records_dropped"] == 3
    assert s["ring_len"] == 4 and s["capacity"] == 4
    v1 = _vals()
    assert _sum(v1, "simon_pulse_records_total") - _sum(
        v0, "simon_pulse_records_total") == 7
    assert _sum(v1, "simon_pulse_records_dropped_total") - _sum(
        v0, "simon_pulse_records_dropped_total") == 3
    pulse.disable()
    assert instruments._DISPATCH_HOOK is None
    assert pulse.active() is None


def test_commit_without_notes_records_nothing():
    p = pulse.enable(capacity=8)
    p.commit_unit(site="fetch", pods=0, wall_s=1e-3)
    assert p.records() == []
    assert p.summary()["records_total"] == 0


# ------------------------------------------------- real-run reconciliation ---


def test_ledger_reconciles_with_census_on_real_run(small_cluster):
    nodes, pods = small_cluster
    run_once(nodes, pods)                     # cold compiles, pulse off
    run_once(nodes, pods)                     # warm oracle
    p = pulse.enable(capacity=4096)
    run_once(nodes, pods)                     # ledger warm-up
    before = len(p.records())
    v0 = _vals()
    run_once(nodes, pods)
    v1 = _vals()
    new = p.records()[before:]
    disp = [r for r in new if r["kind"] == "dispatch"]
    runs = [r for r in new if r["kind"] == "run"]

    d_census = (_sum(v1, "simon_compile_cache_hits_total")
                - _sum(v0, "simon_compile_cache_hits_total")
                + _sum(v1, "simon_compile_cache_misses_total")
                - _sum(v0, "simon_compile_cache_misses_total"))
    d_attempts = (_sum(v1, "simon_scheduling_attempts_total")
                  - _sum(v0, "simon_scheduling_attempts_total"))
    assert disp, "real run produced no attributed dispatch records"
    assert len(disp) == d_census
    assert sum(r["pods"] for r in runs) == d_attempts == len(pods)
    assert (_sum(v1, "simon_pulse_records_total")
            - _sum(v0, "simon_pulse_records_total")) == len(new)
    for r in disp:
        assert r["kernel"] and re.fullmatch(r"[0-9a-f]{16}", r["digest"])
        assert r["site"] in ("dispatch", "fetch")
        assert r["cold"] is False          # everything warmed above
        assert "run" in r                  # attributed to an enclosing run
    for r in runs:
        # table_build is a SLICE of encode (the ROADMAP-5 per-chunk
        # instrument), so it is excluded from the disjoint-phase sum
        disjoint = sum(v for k, v in r["phases"].items()
                       if k != "table_build")
        assert disjoint <= r["wall_s"] * 1.001 + 1e-6
        assert r["phases"].get("table_build", 0.0) <= r["phases"]["encode"]
        assert "dispatch" in r["phases"]


def test_pulse_off_is_bit_identical(small_cluster):
    nodes, pods = small_cluster
    run_once(nodes, pods)                     # warm
    v0 = _vals()
    placed_off, reasons_off = run_once(nodes, pods)
    assert _pulse_deltas(v0, _vals()) == {}, (
        "pulse-off run moved simon_pulse_* samples")
    pulse.enable(capacity=4096)
    placed_on, reasons_on = run_once(nodes, pods)
    assert placed_on == placed_off
    assert reasons_on == reasons_off


# ----------------------------------------------------------- digest keying ---


def test_digest_keying_is_stable_and_audit_compatible():
    p = pulse.enable(capacity=64)
    dims_a = {"N": 8, "P": 4, "mesh": ""}
    dims_b = {"N": 16, "P": 4, "mesh": ""}
    _commit(p, dims=dict(dims_a), cold=True)
    _commit(p, dims=dict(dims_a), cold=False)
    _commit(p, dims=dict(dims_b), cold=True)   # forced recompile: new bucket
    a1, a2, b1 = p.records()
    assert a1["digest"] == a2["digest"]
    assert a1["digest"] != b1["digest"]
    # the ledger key IS the simonaudit runtime digest — one digest family
    assert a1["digest"] == dispatch_digest("schedule_wave", dims_a)
    assert b1["digest"] == dispatch_digest("schedule_wave", dims_b)
    assert (a1["cold"], a2["cold"], b1["cold"]) == (True, False, True)
    rows = {r["digest"]: r for r in p.summary()["kernels"]}
    assert rows[a1["digest"]]["n"] == 2
    assert rows[a1["digest"]]["cold"] == 1
    assert rows[b1["digest"]]["n"] == 1


def test_recompile_on_new_shape_is_cold_under_new_digest(small_cluster):
    nodes, pods = small_cluster
    run_once(nodes, pods)                     # warm the small shape
    p = pulse.enable(capacity=4096)
    run_once(nodes, pods)
    warm_keys = {(r["kernel"], r["digest"]) for r in p.records()
                 if r["kind"] == "dispatch"}
    assert all(not r["cold"] for r in p.records()
               if r["kind"] == "dispatch")
    before = len(p.records())
    big_nodes, big_pods = synth_cluster(128, 60, hard_predicates=True)
    run_once(big_nodes, big_pods)             # new node bucket → recompiles
    new = [r for r in p.records()[before:] if r["kind"] == "dispatch"]
    cold = [r for r in new if r["cold"]]
    assert cold, "new shape bucket produced no cold dispatch records"
    for r in cold:
        assert (r["kernel"], r["digest"]) not in warm_keys, (
            "a recompile reused a warm digest — digest not keyed on shape")


# ------------------------------------------------------------- MAD drift -----


def test_mad_flags_injected_slow_dispatch():
    v0 = _vals()
    p = pulse.enable(capacity=64, mad_window=16, mad_min=8, mad_k=5.0)
    for _ in range(9):
        _commit(p, wall_s=1e-3)
    assert all("regression" not in r for r in p.records())
    _commit(p, wall_s=0.1)                    # ~100x the warm baseline
    slow = p.records()[-1]
    assert slow.get("regression") is True
    assert slow["baseline_med_s"] == pytest.approx(1e-3)
    s = p.summary()
    assert s["regressions_total"] == 1
    (row,) = s["kernels"]
    assert row["regressions"] == 1
    assert row["warm_med_s"] == pytest.approx(1e-3)
    v1 = _vals()
    assert _sum(v1, "simon_pulse_regressions_total") - _sum(
        v0, "simon_pulse_regressions_total") == 1


def test_mad_needs_min_window_before_flagging():
    p = pulse.enable(capacity=64, mad_window=16, mad_min=8, mad_k=5.0)
    for _ in range(5):                        # below mad_min: never flags
        _commit(p, wall_s=1e-3)
    _commit(p, wall_s=0.5)
    assert all("regression" not in r for r in p.records())
    assert p.summary()["regressions_total"] == 0


def test_achieved_roofline_fraction_on_warm_dispatch():
    p = pulse.enable(capacity=64)
    dims = {"N": 8, "P": 4}
    key = ("schedule_wave", dispatch_digest("schedule_wave", dims))
    cost = {"flops": 5e7, "bytes_accessed": 2e7}
    with p._lock:
        p._costs[key] = cost                  # as _harvest_cost would
    opt = pulse.model_optimal_s(cost)
    assert opt > 0.0
    _commit(p, dims=dims, wall_s=2.0 * opt)
    rec = p.records()[-1]
    assert rec["model_optimal_s"] == pytest.approx(opt)
    assert rec["achieved_frac"] == pytest.approx(0.5, abs=1e-6)
    (row,) = p.summary()["kernels"]
    assert row["flops"] == cost["flops"]
    assert row["bytes_accessed"] == cost["bytes_accessed"]
    assert row["achieved_frac"] == pytest.approx(0.5, abs=1e-6)


# ------------------------------------------------------- static roofline -----


def test_roofline_table_covers_all_hot_kernels():
    rows = pulse.roofline_table()
    assert rows, "audit goldens carry no cost fields (run simon audit --update)"
    have = set()
    for r in rows:
        m = re.search(r"(\d+)$", r["mesh"])
        assert m, r
        have.add((r["kernel"], r["bucket"], int(m.group(1))))
        assert r["flops"] >= 0.0 and r["bytes_accessed"] >= 0.0
        assert r["model_optimal_s"] > 0.0
    need = {(k, b, s) for k in kernels.HOT_KERNELS
            for b in ("s16x32", "m48x96") for s in (1, 2, 8)}
    missing = need - have
    assert not missing, f"roofline holes: {sorted(missing)[:6]}"


# --------------------------------------------------------- runs and phases ---


def test_run_window_attributes_dispatches_and_phases():
    v0 = _vals()
    p = pulse.enable(capacity=64)
    with pulse.run_window(pods=5) as run:
        assert run is not None
        pulse.phase("encode", 0.01)
        pulse.phase("dispatch", 0.02)
        pulse.phase("encode", 0.005)
        _commit(p, pods=5)
    disp, runrec = p.records()
    assert disp["run"] == runrec["run"] == run["id"]
    assert runrec["pods"] == 5
    assert runrec["phases"]["encode"] == pytest.approx(0.015)
    assert runrec["phases"]["dispatch"] == pytest.approx(0.02)
    s = p.summary()
    assert s["runs"] == {"n": 1, "pods": 5}
    assert s["phase_seconds"]["encode"] == pytest.approx(0.015)
    v1 = _vals()
    assert _sum(v1, "simon_pulse_phase_seconds_total") - _sum(
        v0, "simon_pulse_phase_seconds_total") == pytest.approx(0.035)


def test_run_window_and_phase_are_noops_when_off():
    v0 = _vals()
    with pulse.run_window(pods=5) as run:
        assert run is None
        pulse.phase("encode", 1.0)
    pulse.note_dispatch("schedule_wave", {"N": 8}, False)  # hookless park
    assert _pulse_deltas(v0, _vals()) == {}


# ------------------------------------------------------------- JSONL spill ---


def test_jsonl_spill_round_trips_through_summarize_records(tmp_path):
    path = tmp_path / "ledger.jsonl"
    p = pulse.enable(capacity=64, jsonl=str(path))      # default size cap
    for i in range(6):
        _commit(p, dims={"N": 8, "P": 4, "i": i % 2}, wall_s=1e-3)
    with pulse.run_window(pods=5):
        pulse.phase("encode", 0.01)
        _commit(p, pods=5)
    live = p.summary()
    pulse.disable()                           # closes the spill file
    spilled = [json.loads(l) for l in
               path.read_text(encoding="utf-8").splitlines() if l]
    assert len(spilled) == live["records_total"] == 8
    offline = pulse.summarize_records(spilled)
    assert offline["records_total"] == 8
    assert offline["runs"] == live["runs"] == {"n": 1, "pods": 5}
    assert offline["phase_seconds"]["encode"] == pytest.approx(0.01)
    live_n = {(r["kernel"], r["digest"]): r["n"] for r in live["kernels"]}
    off_n = {(r["kernel"], r["digest"]): r["n"] for r in offline["kernels"]}
    assert live_n == off_n


def test_jsonl_spill_rotates_at_size_cap(tmp_path):
    path = tmp_path / "ledger.jsonl"
    # ~500-byte cap: ~300-byte records force rotation. Rotation keeps ONE
    # previous generation by design, so the surviving files hold a
    # contiguous SUFFIX of the record stream ending at the newest record.
    p = pulse.enable(capacity=64, jsonl=str(path), jsonl_max_mb=0.0005)
    for i in range(6):
        _commit(p, dims={"N": 8, "P": 4, "i": i}, wall_s=1e-3)
    total = p.summary()["records_total"]
    pulse.disable()
    assert (tmp_path / "ledger.jsonl.1").exists(), "size cap never rotated"
    spilled = []
    for f in (tmp_path / "ledger.jsonl.1", path):
        if f.exists():
            spilled += [json.loads(l) for l in
                        f.read_text(encoding="utf-8").splitlines() if l]
    assert spilled, "rotation left no surviving records"
    seqs = [r["seq"] for r in spilled]
    assert seqs == list(range(seqs[0], total + 1)), (
        f"survivors are not a contiguous suffix ending at {total}: {seqs}")
