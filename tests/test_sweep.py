"""simonsweep: batched scenario sweeps (sweep/).

The contract under test (README "Scenario sweeps", PARITY.md "Sweep
fuzzing"):

- **Batched == serial, every lane.** Each scenario evaluated on the
  scenario axis of a sweep_*_fanout dispatch — through copy-on-write
  drain/activation overlays on one shared resident image — produces a
  per-(node, scheduling-signature) placement census EXACTLY equal to a
  fresh serial Simulator run of that scenario alone. Pods of one group are
  interchangeable, so census equality is placement bit-identity (the
  engine's own stitching rule).
- **Seeded determinism.** Everything random derives from explicit
  SeedSequence keys (seed, family, scenario): same seed = byte-identical
  report JSON; different seed = different Monte-Carlo draws.
- **Routing honesty.** Wave-eligible scenarios ride the wave-chain lane,
  affinity-gated ones the exact scan lane, census-dependent workloads and
  image-declined clusters the fresh path — and every route's result is
  parity-checked the same way.
"""

import copy
import json

import pytest

from open_simulator_tpu.sweep import (
    SweepParityError,
    SweepRunner,
    SweepSpecError,
    build_report,
    compile_families,
    load_spec,
    parse_spec,
    render_report,
    report_json,
)
from open_simulator_tpu.sweep.families import build_base

BASE = {"synthetic": {"nodes": 12, "zones": 3, "cpu": "8", "memory": "16Gi",
                      "bound": 8, "boundCpu": "1", "boundMemory": "1Gi"}}


def make_doc(families, workload=None, base=None, seed=7):
    return {
        "kind": "SweepSpec",
        "metadata": {"name": "test"},
        "spec": {
            "seed": seed,
            "base": base or BASE,
            "workload": workload or [
                {"name": "web", "replicas": 24, "cpu": "1", "memory": "1Gi"},
                {"name": "cache", "replicas": 8, "cpu": "500m",
                 "memory": "512Mi"},
            ],
            "families": families,
        },
    }


def run_doc(doc, **kw):
    kw.setdefault("parity", "full")
    kw.setdefault("fanout", 4)
    runner = SweepRunner(parse_spec(doc), **kw)
    runner.run()
    return runner


# ------------------------------------------------------------- spec layer ----


def test_spec_parse_and_digest_stability():
    doc = make_doc([{"kind": "node_drain", "counts": [1], "draws": 2}])
    spec = parse_spec(doc)
    assert spec.name == "test" and spec.seed == 7
    assert spec.digest() == parse_spec(copy.deepcopy(doc)).digest()
    doc2 = copy.deepcopy(doc)
    doc2["spec"]["workload"][0]["replicas"] = 25
    assert parse_spec(doc2).digest() != spec.digest()


@pytest.mark.parametrize("mutate,needle", [
    (lambda d: d["spec"].pop("families"), "families"),
    (lambda d: d["spec"]["workload"][0].update(priority=10), "priority"),
    (lambda d: d["spec"]["families"].append({"kind": "bogus"}), "unknown family"),
    (lambda d: d["spec"]["families"].append(
        {"kind": "rollout_wave", "workload": "nope", "steps": [50]}),
     "unknown workload"),
    (lambda d: d["spec"].update(base={}), "base"),
    (lambda d: d["spec"]["families"].append(
        {"kind": "node_drain", "counts": [0], "draws": 1}), "counts"),
    (lambda d: d["spec"]["families"].append(
        {"kind": "monte_carlo", "draws": 1, "templates": ["oops"]}),
     "must be mappings"),
])
def test_spec_validation_errors(mutate, needle):
    doc = make_doc([{"kind": "node_drain", "counts": [1], "draws": 1}])
    mutate(doc)
    with pytest.raises(SweepSpecError, match=needle):
        parse_spec(doc)


def test_zone_outage_pairs_need_two_zones():
    """width=2 on a single-zone cluster must refuse loudly, never compile
    an empty grid that reports as if it ran."""
    doc = make_doc([{"kind": "zone_outage", "zones": "all", "width": 2}],
                   base={"synthetic": {"nodes": 6, "zones": 1, "cpu": "8",
                                       "memory": "16Gi"}})
    spec = parse_spec(doc)
    nodes, _ = build_base(spec)
    with pytest.raises(SweepSpecError, match="at least 2 zones"):
        compile_families(spec, 7, nodes)


def test_load_spec_wraps_parse_errors(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("kind: SweepSpec\nspec: [unbalanced\n")
    with pytest.raises(SweepSpecError, match="unparseable"):
        load_spec(str(bad))


def test_family_compilation_is_seed_deterministic():
    doc = make_doc([
        {"kind": "node_drain", "counts": [2], "draws": 3},
        {"kind": "monte_carlo", "draws": 3, "templates": [
            {"name": "mc", "replicas": [1, 40], "cpu": "250m",
             "memory": "256Mi"}]},
    ])
    spec = parse_spec(doc)
    nodes, _ = build_base(spec)
    a = compile_families(spec, 7, nodes)
    b = compile_families(spec, 7, nodes)
    assert [s.drains for s in a.scenarios] == [s.drains for s in b.scenarios]
    assert [len(s.pods) for s in a.scenarios] == [
        len(s.pods) for s in b.scenarios]
    c = compile_families(spec, 8, nodes)
    assert ([s.drains for s in a.scenarios] != [s.drains for s in c.scenarios]
            or [len(s.pods) for s in a.scenarios]
            != [len(s.pods) for s in c.scenarios])
    # explicit PRNG keys recorded per scenario
    assert all(s.key[0] == 7 for s in a.scenarios)


# ------------------------------------------------- batched==serial parity ----


def test_wave_route_parity_all_families():
    """The core fuzz invariant on the wave lane: drains, outages, storms,
    rollouts, pool activations — every lane census equals a fresh serial
    run (SweepRunner raises on any divergence; parity=full checks all)."""
    runner = run_doc(make_doc([
        {"kind": "zone_outage", "zones": "all"},
        {"kind": "node_drain", "counts": [1, 3], "draws": 2},
        {"kind": "preemption_storm", "storms": [6, 16], "cpu": "2",
         "memory": "2Gi"},
        {"kind": "rollout_wave", "workload": "web", "steps": [50, 100],
         "cpu": "1500m", "memory": "1536Mi"},
        {"kind": "nodepool_mix", "counts": [1, 2], "cpu": "16",
         "memory": "32Gi"},
    ]))
    assert runner.parity_checked == len(runner.results)
    assert all(r.route == "wave" for r in runner.results.values())
    # drains/outages really reduce the live node count
    outage = next(r for r in runner.results.values()
                  if r.scenario.family == "zone_outage")
    assert outage.nodes_live < 12
    pool = next(r for r in runner.results.values()
                if r.scenario.family == "nodepool_mix")
    assert pool.nodes_live > 12


def test_scan_route_parity_with_affinity_groups():
    """Self-matching required affinity routes off the wave (the engine's
    own eligibility) onto the per-lane serial-scan kernel; the census
    invariant holds identically there."""
    runner = run_doc(make_doc(
        [{"kind": "node_drain", "counts": [2], "draws": 2},
         {"kind": "monte_carlo", "draws": 2, "templates": [
             {"name": "mc", "replicas": [4, 16], "cpu": "500m",
              "memory": "512Mi"},
             {"name": "pair", "replicas": [2, 6], "cpu": "250m",
              "memory": "256Mi", "affinityOn": "pair"}]}],
        workload=[
            {"name": "web", "replicas": 12, "cpu": "1", "memory": "1Gi"},
            {"name": "pair", "replicas": 6, "cpu": "250m",
             "memory": "256Mi", "affinityOn": "pair"}]))
    routes = {r.route for r in runner.results.values()}
    assert routes == {"scan"}
    assert runner.parity_checked == len(runner.results)


def test_mixed_wave_and_scan_routing():
    """Monte-Carlo draws with affinity templates ride scan while the plain
    drain lanes ride wave — both batched, both parity-checked."""
    runner = run_doc(make_doc([
        {"kind": "node_drain", "counts": [1], "draws": 2},
        {"kind": "monte_carlo", "draws": 2, "templates": [
            {"name": "solo", "replicas": [3, 10], "cpu": "500m",
             "memory": "512Mi", "affinityOn": "solo"}]},
    ]))
    routes = [r.route for _, r in sorted(runner.results.items())]
    assert "wave" in routes and "scan" in routes


def test_census_dependent_workload_routes_fresh():
    """A spread-constrained workload is census-dependent (eligible-domain
    sets read the node census): the image gate routes it to the fresh
    serial path, recorded with its gate reason."""
    from open_simulator_tpu.sweep.families import build_pod
    from open_simulator_tpu.sweep.spec import PodTemplate

    doc = make_doc([{"kind": "node_drain", "counts": [1], "draws": 1}])
    runner = SweepRunner(parse_spec(doc))
    runner.run()
    pods = [build_pod(f"spready-{i}", PodTemplate(name="spready",
                                                 replicas=0))
            for i in range(4)]
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "spready"}}}]
    session = runner.image.session(pods)
    gate = runner.image.eligible(session.batch, pods)
    assert gate is not None and "spread" in gate


def test_image_declined_cluster_runs_fresh_end_to_end():
    """A base cluster the resident image declines (node-advertised images:
    ImageLocality divides by the total node count) runs every scenario on
    the fresh serial path — same report schema, no batched dispatches."""
    doc = make_doc([{"kind": "node_drain", "counts": [1], "draws": 1}])
    spec = parse_spec(doc)
    runner = SweepRunner(spec, parity="full")
    base_nodes, bound = build_base(spec)
    base_nodes[0].setdefault("status", {})["images"] = [
        {"names": ["busybox"], "sizeBytes": 1 << 20}]
    import open_simulator_tpu.sweep.runner as runner_mod

    orig = runner_mod.build_base
    runner_mod.build_base = lambda s: (base_nodes, bound)
    try:
        runner.run()
    finally:
        runner_mod.build_base = orig
    assert runner.image is None
    assert all(r.route == "fresh" for r in runner.results.values())
    report = build_report(runner)
    assert report["lanes"] == {"fresh": 2}
    assert report["parity"]["checked"] == 0  # nothing batched to fuzz


def test_parity_mismatch_raises_loudly():
    """A doctored batched census must fail the sweep (negative control for
    the fuzzer's teeth) and move the mismatch counter."""
    from open_simulator_tpu.obs import REGISTRY

    doc = make_doc([{"kind": "node_drain", "counts": [1], "draws": 1}])
    runner = SweepRunner(parse_spec(doc), parity="off")
    runner.run()
    sid = max(runner.results)
    res = runner.results[sid]
    doctored = dict(res.census)
    key = next(iter(doctored))
    doctored[key] += 1
    runner.results[sid] = res._replace(census=doctored)
    runner.parity = "full"
    before = REGISTRY.values().get(
        "simon_sweep_parity_mismatches_total", 0) or 0
    with pytest.raises(SweepParityError, match="diverged"):
        runner._check_parity()
    after = REGISTRY.values().get("simon_sweep_parity_mismatches_total")
    assert after == before + 1


# ------------------------------------------------------------ determinism ----


def test_report_bytes_identical_across_runs():
    doc = make_doc([
        {"kind": "node_drain", "counts": [2], "draws": 2},
        {"kind": "monte_carlo", "draws": 2, "templates": [
            {"name": "mc", "replicas": [2, 30], "cpu": "500m",
             "memory": "512Mi"}]},
    ])
    j1 = report_json(build_report(run_doc(doc)))
    j2 = report_json(build_report(run_doc(copy.deepcopy(doc))))
    assert j1 == j2
    report = json.loads(j1)
    assert report["seed"] == 7
    # the per-scenario PRNG keys are explicit in the report
    for row in report["scenarios"]:
        assert row["key"][0] == 7


def test_seed_override_changes_draws_and_report():
    doc = make_doc([
        {"kind": "node_drain", "counts": [2], "draws": 2},
        {"kind": "monte_carlo", "draws": 3, "templates": [
            {"name": "mc", "replicas": [1, 60], "cpu": "250m",
             "memory": "256Mi"}]},
    ])
    r1 = run_doc(doc, parity="off")
    r2 = run_doc(copy.deepcopy(doc), parity="off", seed=12345)
    rep1, rep2 = build_report(r1), build_report(r2)
    assert rep1["spec_digest"] == rep2["spec_digest"]  # same spec...
    assert rep1["seed"] != rep2["seed"]                # ...different seed
    mc1 = [r["pods"] for r in rep1["scenarios"]
           if r["family"] == "monte_carlo"]
    mc2 = [r["pods"] for r in rep2["scenarios"]
           if r["family"] == "monte_carlo"]
    assert mc1 != mc2


# ----------------------------------------------------------- report layer ----


def test_report_schema_and_family_metrics():
    runner = run_doc(make_doc([
        {"kind": "preemption_storm", "storms": [10, 20], "cpu": "2",
         "memory": "2Gi"},
        {"kind": "nodepool_mix", "counts": [1, 2], "cpu": "16",
         "memory": "32Gi"},
        {"kind": "zone_outage", "zones": "all"},
    ], workload=[{"name": "web", "replicas": 40, "cpu": "1500m",
                  "memory": "1536Mi"}]))
    report = build_report(runner)
    assert sum(report["lanes"].values()) == len(report["scenarios"])
    storms = report["families"]["preemption_storm"]
    assert [v["storm"] for v in storms["victims"]["per_scenario"]] == [10, 20]
    assert storms["victims"]["max"] >= 0
    env = report["families"]["nodepool_mix"]["capacity_envelope"]
    assert [e["pool"] for e in env] == [1, 2]
    assert env[0]["nodes"] == 13 and env[1]["nodes"] == 14
    # bigger pools never schedule fewer pods (the envelope is monotone)
    assert env[1]["scheduled"] >= env[0]["scheduled"]
    per_zone = report["families"]["zone_outage"]["per_zone"]
    assert len(per_zone) == 3
    text = render_report(report)
    assert "capacity envelope" in text and "victims" in text


def test_storm_victims_count_displaced_baseline_pods():
    """Victims = baseline pods the storm displaces under priority-ordered
    admission, vs the baseline anchor lane."""
    runner = run_doc(make_doc(
        [{"kind": "preemption_storm", "storms": [30], "cpu": "4",
          "memory": "4Gi"}],
        workload=[{"name": "web", "replicas": 40, "cpu": "2",
                   "memory": "2Gi"}]))
    report = build_report(runner)
    baseline = report["scenarios"][0]
    storm_row = next(r for r in report["scenarios"]
                     if r["family"] == "preemption_storm")
    victims = report["families"]["preemption_storm"]["victims"]
    assert victims["per_scenario"][0]["victims"] == (
        baseline["tiers"]["baseline"] - storm_row["tiers"]["baseline"])
    assert victims["per_scenario"][0]["victims"] > 0  # 4-cpu storm displaces


# ---------------------------------------------------------------- kernels ----


def test_wave_chain_padding_segments_are_noops():
    """A lane padded with m=0 segments must equal the same lane without
    padding: the sweep_wave_fanout K axis is pure shape quantization."""
    doc = make_doc([{"kind": "node_drain", "counts": [1], "draws": 1}],
                   workload=[{"name": "web", "replicas": 10, "cpu": "1",
                              "memory": "1Gi"}])
    r1 = run_doc(doc)  # K quantizes to 1 segment
    doc2 = copy.deepcopy(doc)
    doc2["spec"]["workload"] = [
        {"name": "web", "replicas": 10, "cpu": "1", "memory": "1Gi"},
        {"name": "w2", "replicas": 1, "cpu": "250m", "memory": "256Mi"},
        {"name": "w3", "replicas": 1, "cpu": "250m", "memory": "256Mi"},
    ]  # 3 segments -> K=4, one padding segment per lane
    r2 = run_doc(doc2)
    # the shared 'web' placements agree bit-for-bit between the two shapes
    c1 = {k: v for k, v in r1.results[0].census.items()}
    web_sig = {k[1] for k in c1}
    c2 = {k: v for k, v in r2.results[0].census.items() if k[1] in web_sig}
    assert c1 == c2


def test_sweep_counters_move():
    from open_simulator_tpu.obs import REGISTRY

    before = REGISTRY.values()
    runner = run_doc(make_doc([
        {"kind": "node_drain", "counts": [1], "draws": 1}]))
    after = REGISTRY.values()

    def delta(key):
        return (after.get(key) or 0) - (before.get(key) or 0)

    assert delta('simon_sweep_scenarios_total{family="baseline",route="wave"}') == 1
    assert delta('simon_sweep_scenarios_total{family="node_drain",route="wave"}') == 1
    assert delta('simon_sweep_dispatches_total{kernel="sweep_wave_fanout"}') == 1
    assert delta("simon_sweep_parity_checks_total") == 2
    assert delta("simon_sweep_parity_mismatches_total") == 0
    assert sum(runner.dispatches.values()) == 1


# ------------------------------------------------------------- CLI + files ----


def test_example_specs_parse_and_compile():
    import os

    base = os.path.join(os.path.dirname(__file__), "..", "examples",
                        "sweeps")
    names = [f for f in os.listdir(base) if f.endswith(".yaml")]
    assert len(names) >= 3
    for fname in names:
        spec = load_spec(os.path.join(base, fname))
        nodes, _ = build_base(spec)
        compiled = compile_families(spec, spec.seed, nodes)
        assert len(compiled.scenarios) >= 2
        expected = os.path.join(base, fname[:-5] + ".expected.json")
        assert os.path.exists(expected), f"missing snippet for {fname}"
        with open(expected) as fh:
            snip = json.load(fh)
        assert snip["spec_digest"] == spec.digest(), (
            f"{fname}: spec edited without regenerating its expected "
            f"snippet (tools/sweep_smoke.py re-runs zone-outage end-to-end)")
        assert len(snip["scenarios"]) == len(compiled.scenarios)


def test_cli_sweep_writes_deterministic_report(tmp_path):
    from open_simulator_tpu.cli.main import main

    spec_path = tmp_path / "spec.yaml"
    import yaml

    spec_path.write_text(yaml.safe_dump(make_doc(
        [{"kind": "node_drain", "counts": [1], "draws": 1}])))
    out1, out2 = tmp_path / "r1.json", tmp_path / "r2.json"
    assert main(["sweep", str(spec_path), "--out", str(out1),
                 "--seed", "3"]) == 0
    assert main(["sweep", str(spec_path), "--out", str(out2),
                 "--seed", "3"]) == 0
    assert out1.read_bytes() == out2.read_bytes()
    report = json.loads(out1.read_text())
    assert report["kind"] == "SweepReport" and report["seed"] == 3


def test_cli_sweep_rejects_bad_spec(tmp_path, capsys):
    from open_simulator_tpu.cli.main import main

    bad = tmp_path / "bad.yaml"
    bad.write_text("kind: SweepSpec\nspec: {seed: 1}\n")
    assert main(["sweep", str(bad)]) == 1
    assert "sweep error" in capsys.readouterr().err
