"""simonlint analyzer tests: every rule family fires on its fixture, every
suppression suppresses, the real package stays clean, and the @shaped
contract layer validates what it promises."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

import open_simulator_tpu
from open_simulator_tpu.analysis import (
    RULE_REGISTRY,
    Severity,
    analyze_file,
    analyze_paths,
)
from open_simulator_tpu.analysis.base import suppressions_for
from open_simulator_tpu.analysis.runner import run_lint
from open_simulator_tpu.ops import contracts, kernels

FIXTURES = Path(__file__).parent / "analysis_fixtures"
PACKAGE = Path(open_simulator_tpu.__file__).parent


def _counts(path, rule, suppressed=False):
    fr = analyze_file(str(FIXTURES / path))
    assert fr.error is None
    return sum(1 for f in fr.findings
               if f.rule == rule and f.suppressed == suppressed)


# ------------------------------------------------------------ rule families --


def test_host_sync_rule_fires():
    assert _counts("hostsync_hazard.py", "host-sync-in-jit") == 5
    # the .item() in suppressed_pull carries a waiver
    assert _counts("hostsync_hazard.py", "host-sync-in-jit", suppressed=True) == 1


def test_host_sync_spares_host_code():
    fr = analyze_file(str(FIXTURES / "hostsync_hazard.py"))
    # host_side_is_fine() uses np.asarray/float outside any traced context
    assert not any(f.rule == "host-sync-in-jit" and f.line > 44 for f in fr.findings)


def test_recompile_rule_fires():
    fr = analyze_file(str(FIXTURES / "recompile_hazard.py"))
    hits = [f for f in fr.findings if f.rule == "recompile-trigger"]
    named = {f.message.split("'")[1] for f in hits}
    assert named == {"n_buckets", "debug", "shape", "mode"}
    # the static_argnames / static_argnums variants stay clean
    assert not any("scalar_config_ok" in f.message or "_impl_ok" in f.message
                   for f in hits)


def test_dtype_rule_fires():
    assert _counts("dtype_hazard.py", "dtype-drift") == 3
    assert _counts("dtype_hazard.py", "dtype-drift", suppressed=True) == 1


def test_carry_rule_fires():
    fr = analyze_file(str(FIXTURES / "carry_hazard.py"))
    msgs = [f.message for f in fr.findings if f.rule == "carry-contract"]
    assert len(msgs) == 5
    assert any("no carry contract" in m for m in msgs)
    assert any("bare tuple" in m for m in msgs)
    assert any("not its declared contract GoodCarry" in m for m in msgs)
    assert any("1 positional leaves" in m for m in msgs)
    assert any("not a statically resolvable function" in m for m in msgs)
    # clean() at the bottom of the fixture produces nothing
    assert not any(f.line > 55 for f in fr.findings)


def test_contract_spec_rule_fires():
    fr = analyze_file(str(FIXTURES / "contract_hazard.py"))
    hits = [f for f in fr.findings if f.rule == "contract-spec"]
    assert len(hits) == 3
    assert not any(f.line < 10 for f in hits)  # clean_kernel passes


def test_metric_in_jit_rule_fires():
    fr = analyze_file(str(FIXTURES / "metric_injit_hazard.py"))
    hits = [f for f in fr.findings
            if f.rule == "metric-in-jit" and not f.suppressed]
    assert len(hits) == 5
    msgs = "\n".join(f.message for f in hits)
    assert ".inc()" in msgs
    assert ".observe()" in msgs
    assert "time.perf_counter()" in msgs
    assert "open_simulator_tpu.obs.metrics.counter(...)" in msgs
    # the waived inc is reported suppressed, not active
    assert _counts("metric_injit_hazard.py", "metric-in-jit", suppressed=True) == 1


def test_metric_in_jit_spares_at_set_and_host_code():
    fr = analyze_file(str(FIXTURES / "metric_injit_hazard.py"))
    hits = [f for f in fr.findings if f.rule == "metric-in-jit"]
    # at_set_is_fine (the .at[].set functional-update idiom) and the
    # host_side_is_fine dispatch-site instrumentation produce nothing
    src = (FIXTURES / "metric_injit_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def at_set_is_fine" in l)
    supp_start = next(i for i, l in enumerate(src, 1)
                      if "def suppressed_inc" in l)
    assert not any(ok_start <= f.line < supp_start for f in hits)
    host_start = next(i for i, l in enumerate(src, 1)
                      if "def host_side_is_fine" in l)
    assert not any(f.line >= host_start for f in hits)


def test_swallowed_exception_rule_fires():
    fr = analyze_file(str(FIXTURES / "swallowed_hazard.py"))
    hits = [f for f in fr.findings
            if f.rule == "swallowed-exception" and not f.suppressed]
    assert len(hits) == 3
    msgs = "\n".join(f.message for f in hits)
    assert "bare except:" in msgs
    assert "except Exception" in msgs
    assert "broad except tuple" in msgs
    # the whitelisted best-effort block is reported suppressed, not active
    assert _counts("swallowed_hazard.py", "swallowed-exception",
                   suppressed=True) == 1


def test_unsharded_transfer_rule_fires():
    fr = analyze_file(str(FIXTURES / "unsharded_hazard.py"))
    hits = [f for f in fr.findings
            if f.rule == "unsharded-transfer" and not f.suppressed]
    assert len(hits) == 2
    msgs = "\n".join(f.message for f in hits)
    assert "device_put without an explicit sharding" in msgs
    assert "without in_shardings" in msgs
    # the ok_* half declares its layouts (or jits a non-dispatch fn): clean
    src = (FIXTURES / "unsharded_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1) if "def ok_device_put" in l)
    assert not any(f.line >= ok_start for f in hits)


def test_unsharded_transfer_scoped_to_mesh_aware_modules():
    # kernels.py jits dispatch kernels with no in_shardings by design (the
    # single-device variants) — it never imports parallel/, so the rule must
    # not patrol it
    fr = analyze_file(str(PACKAGE / "ops" / "kernels.py"))
    assert not any(f.rule == "unsharded-transfer" for f in fr.findings)


def test_swallowed_exception_spares_handled_paths():
    # narrow types, re-raise, logging, metric counting, error returns, and
    # sys.exit all count as handling — the ok_* half of the fixture is clean
    fr = analyze_file(str(FIXTURES / "swallowed_hazard.py"))
    src = (FIXTURES / "swallowed_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1) if "def ok_narrow" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "swallowed-exception")


def test_clean_module_is_clean():
    fr = analyze_file(str(FIXTURES / "clean_module.py"))
    assert fr.findings == []


def test_naked_dispatch_rule_fires():
    # five direct kernel dispatches fire (incl. schedule_affinity_wave and
    # its fan-out variant); the offline-harness waiver is reported
    # suppressed, not active
    assert _counts("naked_dispatch_hazard.py", "naked-dispatch") == 5
    assert _counts("naked_dispatch_hazard.py", "naked-dispatch",
                   suppressed=True) == 1


def test_naked_dispatch_spares_supervised_forms():
    # lambda / functools.partial / named function / bound-method forms all
    # run under guard.supervised — the guarded_* half of the fixture is clean
    fr = analyze_file(str(FIXTURES / "naked_dispatch_hazard.py"))
    src = (FIXTURES / "naked_dispatch_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1) if "def guarded_lambda" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "naked-dispatch")


def test_unattributed_dispatch_rule_fires():
    # three supervised hot-kernel dispatches with no record_dispatch on the
    # attribution path fire (lambda, partial-through-variable, named
    # function); the offline-harness waiver reports suppressed
    assert _counts("unattributed_dispatch_hazard.py",
                   "unattributed-dispatch") == 3
    assert _counts("unattributed_dispatch_hazard.py",
                   "unattributed-dispatch", suppressed=True) == 1


def test_unattributed_dispatch_spares_attributed_forms():
    # the engine pattern (record_dispatch at the call site), the probe
    # pattern (record_dispatch inside the wrapped body), and supervised
    # host work with no kernel dispatch are all clean
    fr = analyze_file(str(FIXTURES / "unattributed_dispatch_hazard.py"))
    src = (FIXTURES / "unattributed_dispatch_hazard.py").read_text(
        ).splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def attributed_call_site" in l)
    assert not any(f.line >= ok_start and not f.suppressed
                   for f in fr.findings
                   if f.rule == "unattributed-dispatch")


def test_span_outside_guard_rule_fires():
    # three spans (utils/trace.Span x2, scope .span()) around unsupervised
    # kernel dispatches fire; the offline-harness waiver reports suppressed
    assert _counts("span_guard_hazard.py", "span-outside-guard") == 3
    assert _counts("span_guard_hazard.py", "span-outside-guard",
                   suppressed=True) == 1


def test_span_outside_guard_spares_supervised_and_plain_spans():
    # a span AROUND guard.supervised is the sanctioned pattern (the span
    # times a contained dispatch), and spans over host work never fire
    fr = analyze_file(str(FIXTURES / "span_guard_hazard.py"))
    src = (FIXTURES / "span_guard_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def span_around_supervised_is_fine" in l)
    assert not any(f.line >= ok_start and not f.suppressed
                   for f in fr.findings if f.rule == "span-outside-guard")


def test_fetch_in_wave_loop_rule_fires():
    # two loops (per-seg fetch; epoch-poll block+get) yield three findings;
    # the deliberate blocking-probe waiver reports suppressed, not active
    assert _counts("fetch_wave_hazard.py", "fetch-in-wave-loop") == 3
    assert _counts("fetch_wave_hazard.py", "fetch-in-wave-loop",
                   suppressed=True) == 1


def test_fetch_in_wave_loop_spares_spill_points_and_plain_loops():
    # post-loop spills and loops not named per segment/epoch/round are clean
    fr = analyze_file(str(FIXTURES / "fetch_wave_hazard.py"))
    src = (FIXTURES / "fetch_wave_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def ok_post_loop_spill" in l)
    assert not any(f.line >= ok_start and not f.suppressed
                   for f in fr.findings if f.rule == "fetch-in-wave-loop")


def test_collective_in_scan_body_rule_fires():
    # the per-round helper (pmax x2) + a direct all_gather in the while
    # body + a scan psum + a fori pmean fire; the epoch-amortized waiver
    # reports suppressed, not active
    assert _counts("collective_scan_hazard.py", "collective-in-scan-body") == 5
    assert _counts("collective_scan_hazard.py", "collective-in-scan-body",
                   suppressed=True) == 1


def test_collective_in_scan_body_spares_hoisted_and_top_level():
    # a stacked reduce hoisted BEFORE the loop, a top-level collective, and
    # a reducing helper no loop body reaches are the sanctioned patterns
    fr = analyze_file(str(FIXTURES / "collective_scan_hazard.py"))
    src = (FIXTURES / "collective_scan_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def ok_hoisted_stacked_reduce" in l)
    assert not any(f.line >= ok_start and not f.suppressed
                   for f in fr.findings
                   if f.rule == "collective-in-scan-body")


def test_fixture_tree_reports_all_families_and_fails():
    report = analyze_paths([str(FIXTURES)])
    fired = {f.rule for f in report.findings if not f.suppressed}
    assert {"host-sync-in-jit", "recompile-trigger",
            "dtype-drift", "carry-contract", "metric-in-jit",
            "swallowed-exception", "naked-dispatch",
            "fetch-in-wave-loop", "race-unguarded-attr",
            "lock-order-cycle", "entropy-into-report",
            "thread-owner"} <= fired
    assert report.active(Severity.WARNING)
    rc = run_lint([str(FIXTURES)])
    assert rc == 1


# ------------------------------------------------------------- suppressions --


def test_suppression_binds_to_own_line_and_next_line():
    supp = suppressions_for([
        "x = 1  # simonlint: ignore[dtype-drift]",
        "# simonlint: ignore[carry-contract] -- why",
        "y = 2",
        "z = 3",
    ])
    assert supp[1] == frozenset({"dtype-drift"})
    assert supp[3] == frozenset({"carry-contract"})
    assert 4 not in supp


def test_suppression_survives_blank_lines():
    supp = suppressions_for([
        "# simonlint: ignore[dtype-drift] -- why",
        "",
        "v = np.zeros(3, np.float64)",
    ])
    assert supp[3] == frozenset({"dtype-drift"})


def test_suppression_star_and_lists():
    supp = suppressions_for(["a = f()  # simonlint: ignore[r1, r2]"])
    assert supp[1] == frozenset({"r1", "r2"})
    supp = suppressions_for(["a = f()  # simonlint: ignore[*]"])
    assert "*" in supp[1]


# ------------------------------------------------------- the repo stays clean --


def test_package_tree_is_lint_clean():
    """The acceptance gate: no unsuppressed finding anywhere in the package.
    A new hazard must be fixed or carry an explicit reasoned waiver."""
    report = analyze_paths([str(PACKAGE)])
    active = report.active(Severity.WARNING)
    assert active == [], "\n".join(f.human() for f in active)


def test_analysis_pass_is_fast():
    report = analyze_paths([str(PACKAGE)])
    assert report.elapsed_s < 10.0, f"lint took {report.elapsed_s:.2f}s"


# -------------------------------------------------------------- CLI surface --


def test_cli_lint_json_and_exit_codes(tmp_path):
    rc = run_lint([str(FIXTURES / "clean_module.py")])
    assert rc == 0
    bench = tmp_path / "bench.json"
    rc = run_lint(["--format", "json", "--bench-out", str(bench),
                   str(FIXTURES / "dtype_hazard.py")])
    assert rc == 1
    rec = json.loads(bench.read_text())
    assert rec["tool"] == "simonlint"
    assert rec["counts_unsuppressed"]["dtype-drift"] == 3
    assert rec["counts_suppressed"]["dtype-drift"] == 1
    assert rec["elapsed_s"] >= 0


def test_cli_accepts_flags_before_paths():
    from open_simulator_tpu.cli.main import main as cli_main

    rc = cli_main(["lint", "--format", "json",
                   str(FIXTURES / "clean_module.py")])
    assert rc == 0
    rc = cli_main(["lint", "--select", "dtype-drift",
                   str(FIXTURES / "dtype_hazard.py")])
    assert rc == 1


def test_cli_lint_select_and_unknown_rule():
    rc = run_lint(["--select", "dtype-drift", str(FIXTURES / "carry_hazard.py")])
    assert rc == 0  # carry hazards filtered out by --select
    with pytest.raises(SystemExit):
        run_lint(["--select", "no-such-rule", str(FIXTURES)])


@pytest.mark.slow
def test_module_entrypoint_subprocess():
    proc = subprocess.run(
        [sys.executable, "-m", "open_simulator_tpu.cli", "lint", str(FIXTURES)],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 1
    assert "host-sync-in-jit" in proc.stdout


# -------------------------------------------------------- contracts (@shaped) --


def test_parse_spec_roundtrip():
    spec = contracts.parse_spec("[N, R] f32")
    assert spec.dims == ("N", "R") and spec.dtype == "f32"
    assert contracts.parse_spec("[] bool").dims == ()
    assert contracts.parse_spec("any").dims is None
    assert contracts.parse_spec("[N, ...] i32").dims == ("N", "...")
    for bad in ("f99", "[N f32", "[N] ", "[N-1] f32"):
        with pytest.raises(ValueError):
            contracts.parse_spec(bad)


def test_shaped_rejects_unknown_parameter():
    with pytest.raises(TypeError):
        @contracts.shaped(nope="[N] f32")
        def f(x):
            return x


def test_shaped_attaches_contract_and_kernels_declare_them():
    assert contracts.contract_of(kernels.selector_spread_score)
    assert str(contracts.contract_of(kernels.selector_spread_score)["ret"]) == "[N] f32"
    # jit-wrapped kernels keep their contract reachable
    assert contracts.contract_of(kernels.schedule_batch)
    assert contracts.contract_of(kernels.schedule_wave)["cap1"].dtype == "bool"


def test_check_args_enforces_rank_dtype_and_axis_consistency():
    import numpy as np

    @contracts.shaped(a="[N] f32", b="[N] i32")
    def f(a, b):
        return a

    ok_a = np.zeros(4, np.float32)
    ok_b = np.zeros(4, np.int32)
    contracts.check_args(f, ok_a, ok_b)
    with pytest.raises(TypeError):  # dtype mismatch
        contracts.check_args(f, ok_a.astype(np.float64), ok_b)
    with pytest.raises(TypeError):  # rank mismatch
        contracts.check_args(f, ok_a.reshape(2, 2), ok_b)
    with pytest.raises(TypeError):  # symbolic axis inconsistency
        contracts.check_args(f, ok_a, np.zeros(5, np.int32))


# ------------------------------------------- config-scope-across-thread --


def test_config_scope_across_thread_rule_fires():
    # submit/Thread/Timer/run_in_executor inside four jax config scopes
    assert _counts("threadscope_hazard.py", "config-scope-across-thread") == 4
    # the provably-jax-free task carries a reasoned waiver
    assert _counts("threadscope_hazard.py", "config-scope-across-thread",
                   suppressed=True) == 1


def test_config_scope_spares_reentry_and_plain_scopes():
    # re-entering the scope INSIDE the worker (the guard.supervised pattern),
    # submitting after the scope closed, and non-jax `with` blocks are clean
    fr = analyze_file(str(FIXTURES / "threadscope_hazard.py"))
    src = (FIXTURES / "threadscope_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def ok_reenter_scope_in_worker" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "config-scope-across-thread")


# ------------------------------------------------- suppression-reason --


def test_suppression_reason_rule_fires():
    # two bare waivers (trailing + comment-only) are findings...
    assert _counts("bare_waiver_hazard.py", "suppression-reason") == 2
    # ...but still suppress their target rule (the waiver works, the hygiene
    # finding is separate), and the reasoned forms are clean
    assert _counts("bare_waiver_hazard.py", "dtype-drift", suppressed=True) == 4
    assert _counts("bare_waiver_hazard.py", "dtype-drift") == 0


def test_suppression_reason_not_covered_by_star():
    from open_simulator_tpu.analysis.base import Finding, is_suppressed

    supp = suppressions_for(["x = 1  # simonlint: ignore[*]"])
    f = Finding("suppression-reason", Severity.WARNING, "p.py", 1, 0, "m")
    assert not is_suppressed(f, supp)  # a bare star cannot self-suppress
    supp = suppressions_for(
        ["x = 1  # simonlint: ignore[suppression-reason] -- audited waiver"])
    assert is_suppressed(f, supp)  # explicit (reasoned) waiver still works


# ----------------------------------- simonsync: unclassified-network-error --


def test_unclassified_network_error_rule_fires():
    # five unrouted network catches fire; the bookmark-file waiver reports
    # suppressed, not active
    assert _counts("live_netcatch_hazard.py",
                   "unclassified-network-error") == 5
    assert _counts("live_netcatch_hazard.py", "unclassified-network-error",
                   suppressed=True) == 1


def test_unclassified_network_error_scoped_to_live_modules(tmp_path):
    # the identical handlers outside a live path are out of scope — the
    # taxonomy discipline fences live-cluster code only
    mod = tmp_path / "batch_loader.py"
    mod.write_text((FIXTURES / "live_netcatch_hazard.py").read_text())
    fr = analyze_file(str(mod))
    assert not any(f.rule == "unclassified-network-error"
                   for f in fr.findings)


def test_unclassified_network_error_real_live_tier_routes():
    # the shipping live tier must stay compliant: every network catch in
    # simulator/live.py and live/ routes to the typed taxonomy (or carries
    # a reasoned non-network waiver)
    targets = [PACKAGE / "simulator" / "live.py",
               *sorted((PACKAGE / "live").glob("*.py"))]
    for target in targets:
        fr = analyze_file(str(target))
        assert fr.error is None
        active = [f for f in fr.findings
                  if f.rule == "unclassified-network-error"
                  and not f.suppressed]
        assert not active, f"{target}: {[f.line for f in active]}"


# --------------------------------------------------- registry self-test --


def test_every_registered_rule_has_fixture_coverage():
    """New rules can't ship untested: every registered rule id must produce
    at least one finding somewhere in tests/analysis_fixtures/, and the
    clean module must stay clean under the full registry."""
    report = analyze_paths([str(FIXTURES)])
    fired = {f.rule for f in report.findings}  # suppressed findings count
    missing = set(RULE_REGISTRY) - fired
    assert not missing, f"rules with no fixture coverage: {sorted(missing)}"
    assert analyze_file(str(FIXTURES / "clean_module.py")).findings == []


# -------------------------------------------------------------- cache --


def test_cache_roundtrip_identical_findings(tmp_path):
    from open_simulator_tpu.analysis.runner import LintCache

    cpath = str(tmp_path / "cache.json")
    target = str(FIXTURES / "dtype_hazard.py")
    r1 = analyze_paths([target], cache=LintCache(cpath))
    assert (r1.cache_hits, r1.cache_misses) == (0, 1)
    r2 = analyze_paths([target], cache=LintCache(cpath))
    assert (r2.cache_hits, r2.cache_misses) == (1, 0)
    assert ([f.to_json() for f in r2.findings]
            == [f.to_json() for f in r1.findings])


def test_cache_misses_on_content_change_and_select_filters(tmp_path):
    from open_simulator_tpu.analysis.runner import LintCache

    cpath = str(tmp_path / "cache.json")
    mod = tmp_path / "mod.py"
    mod.write_text("import numpy as np\nx = np.zeros(3, np.float64)\n")
    analyze_paths([str(mod)], cache=LintCache(cpath))
    # unchanged: hit, and --select filters the cached full-rule entry
    r = analyze_paths([str(mod)], select=["dtype-drift"],
                      cache=LintCache(cpath))
    assert r.cache_hits == 1
    assert {f.rule for f in r.findings} == {"dtype-drift"}
    # edit: same path, new content hash -> miss, fresh findings
    mod.write_text("import numpy as np\nx = np.zeros(3, np.float32)\n")
    r = analyze_paths([str(mod)], cache=LintCache(cpath))
    assert r.cache_misses == 1
    assert r.findings == []


def test_cache_invalidated_by_ruleset_digest(tmp_path):
    from open_simulator_tpu.analysis.runner import LintCache

    cpath = tmp_path / "cache.json"
    target = str(FIXTURES / "dtype_hazard.py")
    analyze_paths([target], cache=LintCache(str(cpath)))
    doc = json.loads(cpath.read_text())
    assert doc["files"]
    doc["ruleset"] = "0" * 16  # a rule changed since this cache was written
    cpath.write_text(json.dumps(doc))
    stale = LintCache(str(cpath))
    assert stale.files == {}  # fully invalidated, everything re-analyzes


def test_cli_cache_flag_and_exit_codes(tmp_path):
    cpath = str(tmp_path / "cache.json")
    target = str(FIXTURES / "dtype_hazard.py")
    assert run_lint(["--cache", cpath, target]) == 1   # cold
    assert run_lint(["--cache", cpath, target]) == 1   # warm, same verdict


def test_bare_self_waiver_cannot_suppress_suppression_reason():
    from open_simulator_tpu.analysis.base import Finding, is_suppressed

    # a BARE waiver naming the hygiene rule itself must not self-suppress
    supp = suppressions_for(
        ["x = 1  # simonlint: ignore[dtype-drift,suppression-reason]"])
    f = Finding("suppression-reason", Severity.WARNING, "p.py", 1, 0, "m")
    assert not is_suppressed(f, supp)
    assert "dtype-drift" in supp[1]  # the other waiver still works (and is bare)


def test_ruleset_digest_covers_contract_grammar_and_driver(monkeypatch, tmp_path):
    """contract-spec findings depend on ops/contracts.py parse_spec and the
    cache schema lives in runner.py: both must be in the digest's source set,
    and a content change in any listed source must change the digest
    (exercised hermetically on tmp copies, never the tracked files)."""
    import shutil

    from open_simulator_tpu.analysis import runner

    names = [Path(p).name for p in runner._DIGEST_SOURCES]
    assert "contracts.py" in names and "runner.py" in names
    # the flow tier: editing the CFG/taint engine or the lock model must
    # invalidate every cached finding set
    assert "flow.py" in names and "threads.py" in names
    copies = []
    for p in runner._DIGEST_SOURCES:
        dst = tmp_path / Path(p).name
        shutil.copy(p, dst)
        copies.append(str(dst))
    monkeypatch.setattr(runner, "_DIGEST_SOURCES", tuple(copies))
    before = runner.ruleset_digest()
    with open(copies[-1], "ab") as fh:  # the contracts.py copy
        fh.write(b"\n# digest probe\n")
    assert runner.ruleset_digest() != before


def test_suppression_reason_comment_only_waiver_is_waivable(tmp_path):
    """The finding for a comment-only bare waiver anchors to the code line
    the waiver binds to, so a reasoned ignore[suppression-reason] above the
    stack (or trailing on the code line) covers it via the normal
    suppression mechanics."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import numpy as np\n"
        "\n"
        "# simonlint: ignore[suppression-reason] -- audited: generated code\n"
        "# simonlint: ignore[dtype-drift]\n"
        "x = np.zeros(3, np.float64)\n")
    fr = analyze_file(str(mod))
    hits = [f for f in fr.findings if f.rule == "suppression-reason"]
    assert len(hits) == 1 and hits[0].suppressed and hits[0].line == 5


def test_per_pod_host_loop_rule_fires():
    # three per-pod loops in a store-adopted module fire; the gpu-ledger
    # fallback waiver reports suppressed, not active
    assert _counts("perpod_hazard.py", "per-pod-host-loop") == 3
    assert _counts("perpod_hazard.py", "per-pod-host-loop",
                   suppressed=True) == 1


def test_per_pod_host_loop_needs_store_adoption():
    # the same loops in a module that never imports the columnar store are
    # out of scope — the rule fences store-adopted hot paths only
    fr = analyze_file(str(FIXTURES / "hostsync_hazard.py"))
    assert not any(f.rule == "per-pod-host-loop" for f in fr.findings)


def test_per_pod_host_loop_spares_columnar_and_node_loops():
    fr = analyze_file(str(FIXTURES / "perpod_hazard.py"))
    src = (FIXTURES / "perpod_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def vectorized_ok" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "per-pod-host-loop")


# ------------------------------------------------- simonrace: race detector --


def test_race_unguarded_attr_rule_fires():
    # torn-scrape x3, escaping-worker snapshot, module-global peek = 5; the
    # RacyGauge monitoring read carries its happens-before waiver
    assert _counts("race_hazard.py", "race-unguarded-attr") == 5
    assert _counts("race_hazard.py", "race-unguarded-attr",
                   suppressed=True) == 1


def test_race_torn_scrape_regression():
    """The PR 14 known-bug regression: the pre-fix torn-scrape pattern
    (off-lock samples() read of lock-guarded child state) must be reported
    as race-unguarded-attr, with BOTH sites cited — the off-lock read and
    the guarded write it races."""
    fr = analyze_file(str(FIXTURES / "race_hazard.py"))
    hits = [f for f in fr.findings
            if f.rule == "race-unguarded-attr" and not f.suppressed]
    by_attr = {f.message.split("'")[1]: f for f in hits}
    assert {"_children", "_count", "_sum"} <= set(by_attr)
    for attr in ("_count", "_sum"):
        f = by_attr[attr]
        assert "TornScrapeFamily.samples" in f.message  # the off-lock read
        assert "TornScrapeChild.observe" in f.message   # the guarded write
        assert "race_hazard.py:" in f.message           # ...cited by site
        # the child's lock is reached through the typed `family` attribute
        assert "TornScrapeFamily._lock" in f.message


def test_race_spares_locked_convention_and_unshared_classes():
    fr = analyze_file(str(FIXTURES / "race_hazard.py"))
    src = (FIXTURES / "race_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "class LockedCounter" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "race-unguarded-attr")


def test_race_guarded_inference_on_real_metrics_module():
    """Hand-labeled ground truth from obs/metrics.py: MetricFamily owns
    _lock and guards _children; the child classes' histogram state is
    guarded through the typed `family` attribute hop."""
    from open_simulator_tpu.analysis.context import ModuleContext
    from open_simulator_tpu.analysis.threads import module_concurrency

    path = PACKAGE / "obs" / "metrics.py"
    ctx = ModuleContext(str(path), path.read_text())
    mc = module_concurrency(ctx)
    fam = mc.classes["MetricFamily"]
    assert "_lock" in fam.lock_attrs
    assert fam.reachable
    assert fam.guarded["_children"].lock == "MetricFamily._lock"
    hist = mc.classes["_HistChild"]
    for attr in ("_counts", "_sum", "_count"):
        assert hist.guarded[attr].lock == "MetricFamily._lock", attr
    reg = mc.classes["Registry"]
    assert "_lock" in reg.lock_attrs
    assert "_families" in reg.guarded


def test_race_guarded_inference_on_real_batch_module():
    """serve/batch.py ground truth: WhatIfService owns the dispatch
    Condition and guards the queue + stop flag under it."""
    from open_simulator_tpu.analysis.context import ModuleContext
    from open_simulator_tpu.analysis.threads import module_concurrency

    path = PACKAGE / "serve" / "batch.py"
    ctx = ModuleContext(str(path), path.read_text())
    mc = module_concurrency(ctx)
    svc = mc.classes["WhatIfService"]
    assert "_cv" in svc.lock_attrs
    assert svc.reachable  # owns a lock AND its _loop escapes to the thread
    assert svc.escape_lines  # Thread(target=self._loop) marks the escape
    assert svc.guarded["_queue"].lock == "WhatIfService._cv"
    assert svc.guarded["_stopped"].lock == "WhatIfService._cv"


# --------------------------------------------- simonrace: lock-order graph --


def test_lock_order_cycle_rule_fires():
    # the crafted 3-lock cycle fires once (deduped across its 3 rotations);
    # the phase-exclusive 2-lock inversion carries its waiver
    assert _counts("lockorder_hazard.py", "lock-order-cycle") == 1
    assert _counts("lockorder_hazard.py", "lock-order-cycle",
                   suppressed=True) == 1


def test_lock_order_cycle_reports_witness_chain():
    fr = analyze_file(str(FIXTURES / "lockorder_hazard.py"))
    hit = next(f for f in fr.findings
               if f.rule == "lock-order-cycle" and not f.suppressed)
    for hop in ("_ALLOC -> _BILL", "_BILL -> _COMMIT", "_COMMIT -> _ALLOC"):
        assert hop in hit.message
    # each hop cites its acquisition site
    assert hit.message.count("lockorder_hazard.py:") == 3


def test_lock_order_spares_consistent_order_and_reentry():
    fr = analyze_file(str(FIXTURES / "lockorder_hazard.py"))
    src = (FIXTURES / "lockorder_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def outer_then_inner" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "lock-order-cycle")


def test_lock_order_sees_calls_under_lock():
    """An inversion hidden behind a call (A held, callee takes B; elsewhere
    B held, caller takes A) is still a cycle — the transitive acquire
    summary carries it."""
    import textwrap

    from open_simulator_tpu.analysis.context import ModuleContext
    from open_simulator_tpu.analysis.threads import rule_lock_order_cycle

    src = textwrap.dedent("""
        import threading
        _A = threading.Lock()
        _B = threading.Lock()

        def helper():
            with _B:
                pass

        def forward():
            with _A:
                helper()

        def backward():
            with _B:
                with _A:
                    pass
    """)
    ctx = ModuleContext("m.py", src)
    hits = rule_lock_order_cycle(ctx)
    assert len(hits) == 1
    assert "call to 'helper'" in hits[0].message


# ------------------------------------------------ simonrace: thread-owner --


def test_thread_owner_rule_fires():
    # anonymous daemon, named-but-loose, and a Timer fire; the one-shot CLI
    # worker names its owner in the waiver
    assert _counts("threadowner_hazard.py", "thread-owner") == 3
    assert _counts("threadowner_hazard.py", "thread-owner",
                   suppressed=True) == 1


def test_thread_owner_spares_named_daemons_and_joined_threads():
    fr = analyze_file(str(FIXTURES / "threadowner_hazard.py"))
    src = (FIXTURES / "threadowner_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def named_daemon" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "thread-owner")


# ----------------------------------------------- simonflow: entropy taint --


def test_entropy_into_report_rule_fires():
    # direct clock, one-level helper, unseeded random, set iteration = 4;
    # the bench record waives with the artifact named
    assert _counts("entropy_hazard.py", "entropy-into-report") == 4
    assert _counts("entropy_hazard.py", "entropy-into-report",
                   suppressed=True) == 1


def test_entropy_taint_labels_every_source_kind():
    fr = analyze_file(str(FIXTURES / "entropy_hazard.py"))
    msgs = "\n".join(f.message for f in fr.findings
                     if f.rule == "entropy-into-report" and not f.suppressed)
    assert "time.time" in msgs
    assert "_now_ms() [entropy-returning helper]" in msgs
    assert "random.choice" in msgs
    assert "set-iteration-order" in msgs


def test_entropy_spares_sorted_seeded_and_tmp_path_forms():
    fr = analyze_file(str(FIXTURES / "entropy_hazard.py"))
    src = (FIXTURES / "entropy_hazard.py").read_text().splitlines()
    ok_start = next(i for i, l in enumerate(src, 1)
                    if "def sorted_set_is_deterministic" in l)
    assert not any(f.line >= ok_start for f in fr.findings
                   if f.rule == "entropy-into-report")


def test_entropy_helper_summaries_one_level_deep():
    import textwrap

    from open_simulator_tpu.analysis.context import ModuleContext
    from open_simulator_tpu.analysis.flow import entropy_returning_functions

    src = textwrap.dedent("""
        import time

        def _stamp():
            return time.time()

        def _wraps_stamp():
            return {"at": _stamp()}

        def _pure(x):
            return x + 1
    """)
    ctx = ModuleContext("m.py", src)
    fns = entropy_returning_functions(ctx)
    assert "_stamp" in fns
    assert "_wraps_stamp" in fns  # the summary fixpoint carries the chain
    assert "_pure" not in fns


# ------------------------------------------------------ simonflow: the CFG --


def _cfg_of(src):
    import ast as _ast
    import textwrap

    from open_simulator_tpu.analysis import flow

    fn = _ast.parse(textwrap.dedent(src)).body[0]
    return flow.build_cfg(fn)


def test_cfg_if_else_branches_and_join():
    cfg = _cfg_of("""
        def f(x):
            if x:
                a = 1
            else:
                a = 2
            return a
    """)
    labels = {b.label for b in cfg.blocks}
    assert {"if.then", "if.else", "if.after"} <= labels
    # both branches reach the join; the join reaches exit via the return
    after = next(b for b in cfg.blocks if b.label == "if.after")
    preds = cfg.preds()
    assert len(preds[after.id]) == 2
    assert preds[cfg.exit.id]


def test_cfg_while_has_back_edge_and_break_exit():
    cfg = _cfg_of("""
        def f(n):
            while n:
                n -= 1
                if n == 3:
                    break
            return n
    """)
    head = next(b for b in cfg.blocks if b.label == "loop.head")
    after = next(b for b in cfg.blocks if b.label == "loop.after")
    preds = cfg.preds()
    # back edge: some body block links to the head beyond the entry edge
    assert len(preds[head.id]) >= 2
    # break + normal exit both land on loop.after
    assert len(preds[after.id]) >= 2


def test_cfg_try_finally_routes_exceptional_and_normal_paths():
    cfg = _cfg_of("""
        def f(x):
            try:
                y = x()
            except ValueError:
                y = 0
            finally:
                done = True
            return y
    """)
    labels = [b.label for b in cfg.blocks]
    assert "finally" in labels and "except.0" in labels
    fin = next(b for b in cfg.blocks if b.label == "finally")
    preds = cfg.preds()
    # both the protected body and the handler drain through finally
    assert len(preds[fin.id]) >= 2
    handler = next(b for b in cfg.blocks if b.label == "except.0")
    assert preds[handler.id]  # conservative exception edge from the body


def test_cfg_with_as_stays_straight_line():
    cfg = _cfg_of("""
        def f(p):
            with open(p) as fh:
                data = fh.read()
            return data
    """)
    # no branching: everything lives in the entry block
    assert [b for b in cfg.blocks if b.stmts] == [cfg.entry]
    assert cfg.entry.succs == [cfg.exit]


def test_cfg_nested_defs_and_comprehensions_are_opaque():
    import ast as _ast

    cfg = _cfg_of("""
        def f(xs):
            def helper(v):
                while v:
                    v -= 1
                return v
            ys = [helper(x) for x in xs if x]
            return ys
    """)
    # the nested def's while-loop must NOT contribute blocks, and the
    # comprehension must not branch: entry/exit plus nothing else
    assert [b for b in cfg.blocks if b.stmts] == [cfg.entry]
    assert any(isinstance(s, _ast.FunctionDef) for s in cfg.entry.stmts)


def test_dataflow_joins_facts_at_merge_points():
    import ast as _ast
    import textwrap

    from open_simulator_tpu.analysis import flow
    from open_simulator_tpu.analysis.context import ModuleContext

    src = textwrap.dedent("""
        import time

        def f(cond, clean):
            if cond:
                v = time.time()
            else:
                v = clean
            return v
    """)
    ctx = ModuleContext("m.py", src)
    fn = ctx.functions["f"][0]
    eng = flow._TaintEngine(ctx, set())
    cfg = flow.build_cfg(fn)
    facts = flow.dataflow_forward(cfg, eng.transfer)
    # at the join, the tainted branch wins (may-analysis: union)
    after = next(b for b in cfg.blocks if b.label == "if.after")
    assert "v" in facts[after.id]
    assert facts[after.id]["v"][0] == "time.time"
