"""DefaultPreemption (PostFilter) semantics — simulator/preemption.py.

Mirrors the behavior of the reference's default PostFilter plugin
(/root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/framework/plugins/
defaultpreemption/default_preemption.go): victim selection per
selectVictimsOnNode, candidate ranking per pickOneNodeForPreemption, and the
simulator-observable outcome — victims deleted from their nodes, the
preemptor recorded unschedulable with a nominated node (scheduler.go records
the FitError after PostFilter; Simon then deletes the pod,
pkg/simulator/simulator.go:333-342), and later pods seeing the freed capacity.
"""

from __future__ import annotations

from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod


def prio_pod(name, priority, cpu="1", **kw):
    p = make_pod(name, cpu=cpu, **kw)
    p["spec"]["priority"] = priority
    return p


def names_on(sim, node_i=0):
    return sorted(p["metadata"]["name"] for p in sim.pods_on_node[node_i])


def test_basic_preemption_evicts_lowest_importance_victims():
    """selectVictimsOnNode: remove all lower-priority pods, then reprieve
    most-important-first — the surviving victims are the latest-placed ones."""
    nodes = [make_node("n0", cpu="4")]
    lows = [prio_pod(f"low{i}", 0) for i in range(4)]
    high = prio_pod("high", 100, cpu="2")
    sim = Simulator(nodes)
    failed = sim.schedule_pods(lows + [high])
    # the preemptor is still recorded unschedulable (reference behavior), with
    # the nominated node visible on its status
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert failed[0].pod["status"]["nominatedNodeName"] == "n0"
    assert "Insufficient cpu" in failed[0].reason
    # reprieve kept the two earliest-committed victims; the two latest were evicted
    assert names_on(sim) == ["low0", "low1"]
    assert sorted(r["pod"]["metadata"]["name"] for r in sim.preempted) == [
        "low2", "low3"]
    assert all(r["by"] == "high" and r["node"] == "n0" for r in sim.preempted)


def test_freed_capacity_used_by_later_pods():
    """After an eviction, later pods in the same batch schedule into the freed
    space — the serial interleaving the reference's queue produces."""
    nodes = [make_node("n0", cpu="4")]
    lows = [prio_pod(f"low{i}", 0) for i in range(4)]
    high = prio_pod("high", 100, cpu="4")  # evicts all four, still recorded failed
    med = prio_pod("med", 50, cpu="2")     # schedules into the freed node
    sim = Simulator(nodes)
    failed = sim.schedule_pods(lows + [high, med])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert names_on(sim) == ["med"]
    assert len(sim.preempted) == 4


def test_preemption_interleaves_with_scheduling():
    """fail→evict→next-identical-pod-schedules alternation across wave-sized
    groups of identical pods: each failed high pod frees exactly one slot,
    which the NEXT high pod takes."""
    nodes = [make_node("n0", cpu="8"), make_node("n1", cpu="8")]
    lows = [prio_pod(f"low{i}", 0, labels={"app": "low"}) for i in range(16)]
    highs = [prio_pod(f"high{i}", 100, labels={"app": "high"}) for i in range(4)]
    sim = Simulator(nodes)
    failed = sim.schedule_pods(lows + highs)
    fail_names = [u.pod["metadata"]["name"] for u in failed]
    assert fail_names == ["high0", "high2"]  # high1/high3 take the freed slots
    assert len(sim.preempted) == 2
    placed = [p for i in range(2) for p in sim.pods_on_node[i]]
    assert sum(p["metadata"]["labels"]["app"] == "high" for p in placed) == 2
    assert sum(p["metadata"]["labels"]["app"] == "low" for p in placed) == 14


def test_preempt_never_policy_blocks_eviction():
    """PodEligibleToPreemptOthers: preemptionPolicy Never ⇒ no preemption."""
    nodes = [make_node("n0", cpu="4")]
    lows = [prio_pod(f"low{i}", 0) for i in range(4)]
    high = prio_pod("high", 100, cpu="2")
    high["spec"]["preemptionPolicy"] = "Never"
    sim = Simulator(nodes)
    failed = sim.schedule_pods(lows + [high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert "nominatedNodeName" not in (failed[0].pod.get("status") or {})
    assert names_on(sim) == ["low0", "low1", "low2", "low3"]
    assert sim.preempted == []


def test_unresolvable_nodes_are_not_candidates():
    """nodesWherePreemptionMightHelp: a node failing on taints
    (UnschedulableAndUnresolvable, taint_toleration.go:71) is skipped; the
    eviction lands on the resource-full node."""
    tainted = make_node("nA", cpu="8", taints=[
        {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}])
    full = make_node("nB", cpu="1")
    lows = [prio_pod("low0", 0, cpu="1")]
    high = prio_pod("high", 100, cpu="1")
    sim = Simulator([tainted, full])
    failed = sim.schedule_pods(lows + [high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert failed[0].pod["status"]["nominatedNodeName"] == "nB"
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["low0"]


def test_no_candidates_when_every_failure_is_unresolvable():
    """All nodes fail on node affinity ⇒ preemption cannot help; nothing is
    evicted (interpodaffinity-style unresolvable statuses keep victims safe)."""
    nodes = [make_node("n0", cpu="1", labels={"disk": "hdd"})]
    lows = [prio_pod("low0", 0, cpu="1")]
    high = prio_pod("high", 100, cpu="1", node_selector={"disk": "ssd"})
    sim = Simulator(nodes)
    failed = sim.schedule_pods(lows + [high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert sim.preempted == []
    assert names_on(sim) == ["low0"]


def test_victims_are_the_lowest_priority_pods():
    """Reprieve runs most-important-first, so the lowest-priority pod on the
    node is the one evicted."""
    nodes = [make_node("n0", cpu="3")]
    a = prio_pod("a", 5)
    b = prio_pod("b", 1)
    c = prio_pod("c", 3)
    high = prio_pod("high", 100, cpu="1")
    sim = Simulator(nodes)
    failed = sim.schedule_pods([a, b, c, high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["b"]
    assert names_on(sim) == ["a", "c"]


def test_pick_node_minimizes_highest_victim_priority():
    """pickOneNodeForPreemption criterion 2: the node whose top victim has the
    lower priority wins."""
    nodes = [make_node("nA", cpu="1"), make_node("nB", cpu="1")]
    va = prio_pod("va", 10, node_name="nA")
    vb = prio_pod("vb", 5, node_name="nB")
    high = prio_pod("high", 100, cpu="1")
    sim = Simulator(nodes)
    failed = sim.schedule_pods([va, vb, high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["vb"]
    assert failed[0].pod["status"]["nominatedNodeName"] == "nB"


def test_pdb_covered_victims_reprieved_first():
    """selectVictimsOnNode reprieves PDB-violating victims before others, so
    the PDB-covered pod survives and the uncovered one is evicted."""
    nodes = [make_node("n0", cpu="2")]
    covered = prio_pod("covered", 0, labels={"app": "db"})
    free = prio_pod("free", 0, labels={"app": "web"})
    high = prio_pod("high", 100, cpu="1")
    sim = Simulator(nodes)
    sim.model.pdbs.append({
        "kind": "PodDisruptionBudget",
        "metadata": {"name": "db-pdb", "namespace": "default"},
        "spec": {"selector": {"matchLabels": {"app": "db"}}},
        "status": {"disruptionsAllowed": 0},
    })
    failed = sim.schedule_pods([covered, free, high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["free"]
    assert names_on(sim) == ["covered"]


def test_pick_node_prefers_no_pdb_violations():
    """pickOneNodeForPreemption criterion 1: a candidate whose eviction
    violates no PDB beats one that would violate."""
    nodes = [make_node("nA", cpu="1"), make_node("nB", cpu="1")]
    va = prio_pod("va", 0, node_name="nA", labels={"app": "db"})
    vb = prio_pod("vb", 0, node_name="nB", labels={"app": "web"})
    high = prio_pod("high", 100, cpu="1")
    sim = Simulator(nodes)
    sim.model.pdbs.append({
        "kind": "PodDisruptionBudget",
        "metadata": {"name": "db-pdb", "namespace": "default"},
        "spec": {"selector": {"matchLabels": {"app": "db"}}},
        "status": {"disruptionsAllowed": 0},
    })
    failed = sim.schedule_pods([va, vb, high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["vb"]


def test_preemption_across_schedule_calls():
    """Cluster pods and app pods schedule in separate calls; a high-priority
    app pod preempts cluster pods placed in the earlier call."""
    nodes = [make_node("n0", cpu="2")]
    sim = Simulator(nodes)
    assert sim.schedule_pods([prio_pod(f"low{i}", 0) for i in range(2)]) == []
    failed = sim.schedule_pods([prio_pod("high", 100, cpu="2")])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert len(sim.preempted) == 2
    assert names_on(sim) == []


def test_preemption_disabled_by_scheduler_config(tmp_path):
    """plugins.postFilter.disabled: [DefaultPreemption] turns the pass off."""
    from open_simulator_tpu.api.schedconfig import parse_scheduler_config

    cfg = tmp_path / "sched.yaml"
    cfg.write_text(
        "apiVersion: kubescheduler.config.k8s.io/v1beta1\n"
        "kind: KubeSchedulerConfiguration\n"
        "profiles:\n"
        "- schedulerName: default-scheduler\n"
        "  plugins:\n"
        "    postFilter:\n"
        "      disabled:\n"
        "      - name: DefaultPreemption\n")
    sc = parse_scheduler_config(str(cfg))
    assert sc.preemption_disabled
    nodes = [make_node("n0", cpu="2")]
    lows = [prio_pod(f"low{i}", 0) for i in range(2)]
    high = prio_pod("high", 100, cpu="1")
    sim = Simulator(nodes, sched_config=sc)
    failed = sim.schedule_pods(lows + [high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert sim.preempted == []
    assert names_on(sim) == ["low0", "low1"]


def test_no_lower_priority_no_attempt():
    """A failed pod with no strictly-lower-priority pod placed anywhere never
    preempts (uniform-priority inertness, the round-3 proof, now enforced by
    the armed path too)."""
    nodes = [make_node("n0", cpu="2")]
    sim = Simulator(nodes)
    # mixed priorities arm the pass, but the FAILING pod is the low one
    pods = [prio_pod("high0", 100), prio_pod("high1", 100),
            prio_pod("low", 0, cpu="2")]
    failed = sim.schedule_pods(pods)
    assert [u.pod["metadata"]["name"] for u in failed] == ["low"]
    assert sim.preempted == []
    assert names_on(sim) == ["high0", "high1"]


def test_anti_affinity_failure_is_resolvable():
    """A node failing only on another pod's required anti-affinity is a valid
    candidate (Unschedulable, not UnschedulableAndUnresolvable): evicting the
    carrier makes room."""
    nodes = [make_node("n0", cpu="8")]
    blocker = prio_pod("blocker", 0, labels={"app": "solo"})
    blocker["spec"]["affinity"] = {"podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [{
            "topologyKey": "kubernetes.io/hostname",
            "labelSelector": {"matchLabels": {"app": "web"}},
        }]}}
    high = prio_pod("high", 100, labels={"app": "web"})
    sim = Simulator(nodes)
    failed = sim.schedule_pods([blocker, high])
    assert [u.pod["metadata"]["name"] for u in failed] == ["high"]
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["blocker"]
    assert names_on(sim) == []


def test_higher_priority_same_spec_pod_gets_own_attempt():
    """The attempted-dedup is keyed by (signature, priority): after a
    low-priority pod's failed attempt, a later pod with the SAME spec but a
    HIGHER priority sees a larger victim pool and must not be skipped."""
    nodes = [make_node("n0", cpu="4")]
    tiny = prio_pod("tiny", 0, cpu="1")
    mid = prio_pod("mid", 50, cpu="3")
    atk_low = prio_pod("atk-low", 10, cpu="2", labels={"app": "atk"})
    atk_high = prio_pod("atk-high", 100, cpu="2", labels={"app": "atk"})
    sim = Simulator(nodes)
    failed = sim.schedule_pods([tiny, mid, atk_low, atk_high])
    # atk-low attempts (tiny is strictly lower) but evicting tiny frees only
    # 1 cpu — no candidate; atk-high's pool includes mid and must succeed
    assert sorted(u.pod["metadata"]["name"] for u in failed) == [
        "atk-high", "atk-low"]
    assert [r["pod"]["metadata"]["name"] for r in sim.preempted] == ["mid"]
    high_rec = next(u for u in failed
                    if u.pod["metadata"]["name"] == "atk-high")
    assert high_rec.pod["status"]["nominatedNodeName"] == "n0"
    assert names_on(sim) == ["tiny"]
