"""simonsync: resilient live-cluster watch sync (live/sync.py, live/decode.py).

The contract under test (README "Live sync", ISSUE PR 20):

- **Chaos convergence.** A seeded chaos run — connection flaps, duplicate
  deliveries, in-window reorders, 410-Gone compactions — converges to an
  image bit-identical to the flap-free replay of the same recorded stream:
  same host truth, same epoch lineage (one seq per bookmark window, relist
  windows included), same what-if answers, zero full rebuilds.
- **Exactly-once apply.** Three dedup layers (bookmark stale filter,
  per-(kind,key) resourceVersion table, presence probe against the resident
  image with per-batch staging) make redelivery and reorder no-ops; batches
  apply sorted by rv at server-declared safe points only.
- **Relist reconciliation.** A compacted horizon (410) recovers by listing
  current state and diffing it columnar against the resident image —
  delta events only, one batch, never a generation-bumping rebuild — and
  the reconciled image equals a from-scratch build over the listed state.
- **Crash-exact resume.** Every applied batch rides the simonha WAL behind
  a prev/next/expected-seq bookmark stamp written before the apply, so a
  SIGKILL anywhere resumes from (checkpoint + WAL tail + bookmark) without
  double-applying or dropping a window.
- **Deterministic recovery.** Reconnect backoff comes from the seeded
  RetryPolicy schedule: the same fault plan replays the same sleeps and the
  same injection trace (the simonfault contract, sites watch_read /
  watch_parse / watch_gone / relist).
"""

import json

import pytest

from open_simulator_tpu.live import (
    ProtocolError,
    QueueSource,
    RecordedSource,
    ScriptedSource,
    TemplateInterner,
    WatchSync,
    parse_line,
)
from open_simulator_tpu.resilience import FaultPlan, installed
from open_simulator_tpu.serve import HAState, ResidentImage
from open_simulator_tpu.server.http import ClusterSnapshot, Server
from open_simulator_tpu.core.types import ResourceTypes
from open_simulator_tpu.utils.synth import synth_node, synth_watch_stream

from test_serve import assert_same_response, whatif_pods

CHAOS = dict(flap_p=0.02, dup_p=0.05, reorder_p=0.05, gone_p=0.25)


def _stream(n_nodes=40, n_events=300, seed=7, bookmark_every=24, n_bound=30):
    return synth_watch_stream(n_nodes, n_events, seed=seed,
                              bookmark_every=bookmark_every, n_bound=n_bound)


def _image(nodes, bound):
    img = ResidentImage.try_build(
        [json.loads(json.dumps(n)) for n in nodes],
        pods=[json.loads(json.dumps(p)) for p in bound])
    assert img is not None
    return img


def _truth(image):
    pods, live = image.sync_snapshot()
    return json.dumps({"pods": sorted(pods.items()), "nodes": sorted(live)},
                      sort_keys=True)


def _oracle(nodes, bound, lines):
    """The flap-free replay every chaos run must converge to."""
    img = _image(nodes, bound)
    stats = WatchSync(RecordedSource(lines=lines), image=img).run()
    return img, stats


def _line(typ, obj):
    return json.dumps({"type": typ, "object": obj})


def _pod_line(typ, name, rv, node="node-00000", ns="default"):
    return _line(typ, {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns,
                     "resourceVersion": str(rv)},
        "spec": {"nodeName": node,
                 "containers": [{"name": "app", "resources": {
                     "requests": {"cpu": "100m", "memory": "64Mi"}}}]}})


def _bookmark(rv):
    return _line("BOOKMARK", {"kind": "Pod",
                              "metadata": {"resourceVersion": str(rv)}})


# ------------------------------------------------------- chaos convergence ----


@pytest.mark.parametrize("seed", [3, 11, 42])
def test_chaos_convergence_bit_identical(seed):
    """The acceptance oracle: flaps + 410s + duplicates + reorders converge
    to the flap-free image — host truth, epoch lineage, zero rebuilds."""
    nodes, bound, lines = _stream()
    oracle, _ = _oracle(nodes, bound, lines)

    img = _image(nodes, bound)
    src = ScriptedSource(lines, seed=seed, base_nodes=nodes,
                         base_pods=bound, **CHAOS)
    sync = WatchSync(src, image=img, sleep=lambda s: None)
    stats = sync.run()

    assert src.gones_planned or src.flaps_planned  # chaos actually planned
    assert _truth(img) == _truth(oracle)
    assert img.epoch == oracle.epoch
    assert img.generation == 1 and stats["full_rebuilds"] == 0
    assert stats["parity_mismatches"] == 0


def test_chaos_whatif_answers_match_oracle():
    """Host truth converging is necessary; the serving answer converging is
    the point. The chaos image answers what-ifs identically to the
    flap-free image AND to its own serial fresh-encode probe."""
    nodes, bound, lines = _stream()
    oracle, _ = _oracle(nodes, bound, lines)
    img = _image(nodes, bound)
    WatchSync(ScriptedSource(lines, seed=11, base_nodes=nodes,
                             base_pods=bound, **CHAOS),
              image=img, sleep=lambda s: None).run()
    for tag, n in (("a", 4), ("b", 7)):
        req = whatif_pods(tag, n)
        got = img.session(req).run()
        assert_same_response(got, oracle.fresh_probe(req))
        assert_same_response(got, img.fresh_probe(req))


# ------------------------------------------------------------ dedup layers ----


def test_duplicate_rv_delivery_applies_once():
    nodes = [synth_node(0)]
    img = _image(nodes, [])
    lines = [_pod_line("ADDED", "p-1", 5),
             _pod_line("ADDED", "p-1", 5),  # wire redelivery, same rv
             _bookmark(6)]
    stats = WatchSync(RecordedSource(lines=lines), image=img).run()
    assert stats["applied"] == 1 and stats["duplicates"] == 1
    assert img.has_pod("default/p-1")


def test_presence_dedup_is_per_batch_staged():
    """add -> delete -> re-add of one key inside a single window must stage
    through (final state present), while a re-add of an already-resident
    pod with a fresh rv is recognized as a presence duplicate."""
    nodes = [synth_node(0)]
    img = _image(nodes, [])
    lines = [_pod_line("ADDED", "p-1", 5),
             _pod_line("DELETED", "p-1", 6),
             _pod_line("ADDED", "p-1", 7),
             _bookmark(8),
             _pod_line("ADDED", "p-1", 9),  # new rv, but already resident
             _bookmark(10)]
    stats = WatchSync(RecordedSource(lines=lines), image=img).run()
    assert img.has_pod("default/p-1")
    assert stats["applied"] == 3  # the staged add/delete/add all land
    assert stats["duplicates"] == 1  # the post-bookmark re-add is presence-deduped


def test_out_of_order_window_applies_sorted():
    """Wire reorder inside a window never changes the applied order: the
    batch sorts by rv, so delete-then-add arriving as add-then-delete
    still nets to the rv-ordered outcome."""
    nodes = [synth_node(0)]
    base_pod = _pod_line("ADDED", "p-1", 5)
    inorder = [base_pod, _bookmark(6),
               _pod_line("DELETED", "p-1", 7),
               _pod_line("ADDED", "p-1", 8),
               _bookmark(9)]
    reordered = [base_pod, _bookmark(6),
                 _pod_line("ADDED", "p-1", 8),
                 _pod_line("DELETED", "p-1", 7),
                 _bookmark(9)]
    img_a = _image(nodes, [])
    img_b = _image(nodes, [])
    WatchSync(RecordedSource(lines=inorder), image=img_a).run()
    WatchSync(RecordedSource(lines=reordered), image=img_b).run()
    assert _truth(img_a) == _truth(img_b)
    assert img_a.epoch == img_b.epoch
    assert img_a.has_pod("default/p-1")


def test_stale_replay_before_bookmark_filtered():
    """Reconnecting a source that replays from before our bookmark (the
    recorded-stream shape) drops everything at-or-under the bookmark."""
    nodes = [synth_node(0)]
    img = _image(nodes, [])
    lines = [_pod_line("ADDED", "p-1", 5), _bookmark(6)]
    sync = WatchSync(RecordedSource(lines=lines), image=img)
    sync.run()
    assert sync.bookmark == 6
    seq0 = img.seq
    stats = WatchSync.run(sync)  # second pass over the same recorded lines
    assert stats["stale"] >= 1 and img.seq == seq0
    assert stats["applied"] == 1  # nothing new applied beyond the first run


def test_skip_only_window_advances_bookmark_without_seq():
    """A window whose events all decode to skips (unbound pods) advances
    the bookmark but never bumps the epoch — bookmark-only persistence."""
    nodes = [synth_node(0)]
    img = _image(nodes, [])
    unbound = _line("ADDED", {
        "kind": "Pod", "metadata": {"name": "ghost", "namespace": "default",
                                    "resourceVersion": "5"},
        "spec": {}})
    seq0 = img.seq
    sync = WatchSync(RecordedSource(lines=[unbound, _bookmark(6)]), image=img)
    stats = sync.run()
    assert stats["skipped"] == 1 and stats["applied"] == 0
    assert sync.bookmark == 6 and img.seq == seq0


# ------------------------------------------------------ relist reconcile ----


def test_relist_reconcile_equals_from_scratch_rebuild():
    """Doctored gaps: every eligible window is compacted away (gone_p=1),
    forcing relist after relist. The reconciled image must equal a
    from-scratch build over the source's listed state — with generation 1
    (delta events only, never a rebuild)."""
    nodes, bound, lines = _stream(n_events=240, seed=9)
    img = _image(nodes, bound)
    src = ScriptedSource(lines, seed=4, gone_p=1.0, base_nodes=nodes,
                         base_pods=bound)
    sync = WatchSync(src, image=img, sleep=lambda s: None)
    stats = sync.run()
    assert stats["relists"] >= 1
    assert stats["full_rebuilds"] == 0 and img.generation == 1
    assert stats["parity_mismatches"] == 0

    final_rv, listed_nodes, listed_pods = src.list()
    # try_build commits every node it is handed; the listed state carries
    # drained nodes as spec.unschedulable markers, so drop them here
    live_only = [n for n in listed_nodes
                 if not (n.get("spec") or {}).get("unschedulable")]
    fresh = _image(live_only, listed_pods)
    pods_a, live_a = img.sync_snapshot()
    pods_b, live_b = fresh.sync_snapshot()
    assert (sorted(pods_a.items()), sorted(live_a)) == (
        sorted(pods_b.items()), sorted(live_b))


def test_relist_gap_costs_exactly_one_seq():
    """Epoch parity through a gap: the reconcile batch costs exactly the
    seq the swallowed window would have — chaos epoch == clean epoch."""
    nodes, bound, lines = _stream(n_events=120, seed=13)
    oracle, _ = _oracle(nodes, bound, lines)
    img = _image(nodes, bound)
    src = ScriptedSource(lines, seed=2, gone_p=1.0, base_nodes=nodes,
                         base_pods=bound)
    stats = WatchSync(src, image=img, sleep=lambda s: None).run()
    assert stats["relists"] >= 1
    assert img.epoch == oracle.epoch
    assert _truth(img) == _truth(oracle)


# -------------------------------------------------- crash-exact resume ----


class _KillAfter:
    """Source wrapper that raises mid-stream after n lines — the in-process
    stand-in for SIGKILL (tools/sync_smoke.py kills a real process)."""

    class Boom(BaseException):
        pass

    def __init__(self, inner, n):
        self.inner, self.n, self.count = inner, n, 0

    def watch(self, since_rv):
        for line in self.inner.watch(since_rv):
            self.count += 1
            if self.count > self.n:
                raise self.Boom()
            yield line

    def list(self):
        return self.inner.list()


@pytest.mark.parametrize("seed,kill_at", [(7, 40), (23, 130), (101, 201)])
def test_sigkill_resume_bit_identity(seed, kill_at, tmp_path):
    """Kill the consumer mid-stream (WAL and bookmark left wherever the
    crash caught them), reopen the state dir, resume from
    (checkpoint + WAL tail + bookmark), and require the final image be
    bit-identical to the never-crashed chaos-free oracle."""
    nodes, bound, lines = _stream(n_nodes=30, n_events=240, seed=5,
                                  bookmark_every=20, n_bound=20)
    oracle, _ = _oracle(nodes, bound, lines)

    def build():
        return _image(nodes, bound)

    ha1 = HAState.open(str(tmp_path), build, checkpoint_every=4)
    src = ScriptedSource(lines, seed=seed, base_nodes=nodes,
                         base_pods=bound, **CHAOS)
    sync1 = WatchSync(_KillAfter(src, kill_at), ha=ha1,
                      sleep=lambda s: None)
    with pytest.raises(_KillAfter.Boom):
        sync1.run()
    # crash: abandon ha1 unclosed; reopen replays checkpoint + WAL tail
    ha2 = HAState.open(str(tmp_path), build, checkpoint_every=4)
    sync2 = WatchSync(src, ha=ha2, sleep=lambda s: None)
    stats = sync2.run()
    assert _truth(ha2.image) == _truth(oracle)
    assert ha2.image.epoch == oracle.epoch
    assert stats["full_rebuilds"] == 0 and stats["parity_mismatches"] == 0
    ha2.close()


# ------------------------------------------------ deterministic recovery ----


def test_reconnect_backoff_is_bit_replayable():
    """Two fresh consumers over identically-seeded flapping sources sleep
    the exact same schedule — recovery is part of the replayable run."""
    nodes, bound, lines = _stream(n_events=160, seed=17)
    sleeps = []
    for _ in range(2):
        img = _image(nodes, bound)
        src = ScriptedSource(lines, seed=21, flap_p=0.12,
                             base_nodes=nodes, base_pods=bound)
        sync = WatchSync(src, image=img, sleep=lambda s: None)
        sync.run()
        sleeps.append(list(sync.sleeps))
    assert sleeps[0], "no flap fired — chaos knob lost its bite"
    assert sleeps[0] == sleeps[1]


def test_fault_sites_replay_equal(tmp_path):
    """Every simonsync fault site, injected twice with the same plan, fires
    the same trace and still converges to the oracle (the simonfault
    contract extended to the watch path)."""
    nodes, bound, lines = _stream(n_events=120, seed=19)
    oracle, _ = _oracle(nodes, bound, lines)
    for site, error in (("watch_read", "transient"),
                        ("watch_parse", "transient"),
                        ("watch_gone", "protocol"),
                        ("relist", "transient")):
        traces = []
        for rep in range(2):
            img = _image(nodes, bound)
            # the relist site only runs inside 410 recovery, so its fault
            # plan rides a source whose windows actually compact away
            src = ScriptedSource(
                lines, seed=1, base_nodes=nodes, base_pods=bound,
                gone_p=1.0 if site == "relist" else 0.0)
            sync = WatchSync(src, image=img, sleep=lambda s: None)
            plan = FaultPlan.from_json({"faults": [
                {"site": site, "attempt": 2, "error": error}]})
            with installed(plan) as active:
                stats = sync.run()
                traces.append(list(active.trace))
            assert _truth(img) == _truth(oracle), site
            assert stats["full_rebuilds"] == 0, site
            if site in ("watch_gone", "relist"):
                assert stats["relists"] >= 1, site
            else:
                assert stats["reconnects"] >= 1, site
        assert traces[0] == traces[1], site
        assert traces[0], site  # the site actually fired


# ----------------------------------------------------- decode unit layer ----


def test_parse_line_typed_errors():
    with pytest.raises(ProtocolError):
        parse_line("{not json")
    with pytest.raises(ProtocolError):
        parse_line(json.dumps({"type": "FROBNICATED", "object": {}}))
    with pytest.raises(ProtocolError) as ei:
        parse_line(json.dumps({"type": "ERROR", "object": {
            "code": 410, "message": "too old resource version"}}))
    assert ei.value.code == 410


def test_template_interner_shares_subtrees_not_identity():
    """Interned pods share labels/spec template blocks (dict-free decode)
    but stay distinct top-level objects — the image's identity-keyed
    bookkeeping (`id(pod)`) must never see aliased pods."""
    interner = TemplateInterner()
    raw = json.loads(_pod_line("ADDED", "p-1", 5))["object"]
    raw2 = json.loads(_pod_line("ADDED", "p-2", 6))["object"]
    a, b = interner.pod(raw), interner.pod(raw2)
    assert a is not b
    assert a["metadata"]["labels"] is b["metadata"]["labels"]
    assert interner.hits >= 1


def test_queue_source_backpressure_bound():
    q = QueueSource(maxsize=2)
    q.push("a")
    q.push("b")
    assert q._q.full()  # a stalled consumer back-pressures the producer


# -------------------------------------------------------- server wiring ----


def test_server_start_watch_feeds_resident_image(tmp_path):
    """`--watch file:PATH` end to end: the server starts a WatchSync over
    the recorded stream, the resident image converges to the flap-free
    oracle, and /v1/serve/stats carries the sync stats block."""
    nodes, bound, lines = _stream(n_nodes=12, n_events=80,
                                  bookmark_every=16, n_bound=8)
    oracle, _ = _oracle(nodes, bound, lines)
    rec = tmp_path / "stream.jsonl"
    rec.write_text("\n".join(lines) + "\n")

    rt = ResourceTypes(nodes=[json.loads(json.dumps(n)) for n in nodes],
                       pods=[json.loads(json.dumps(p)) for p in bound])
    snap = ClusterSnapshot(rt, [], [], [])
    server = Server(snapshot_fn=lambda: snap, whatif=True,
                    watch=f"file:{rec}")
    assert server.start_watch()
    for t in server._sync_threads:
        t.join(timeout=30.0)
    stats = server.sync_stats()
    assert stats and not stats.get("errors")
    assert stats["sources"][0]["applied"] > 0
    img = server.whatif_service().image
    assert _truth(img) == _truth(oracle)
    assert img.epoch == oracle.epoch
