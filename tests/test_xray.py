"""simonxray flight-recorder tests.

The acceptance contract: recording must be a pure OBSERVER — placements,
failure reasons, and probe counts bit-identical with recording on vs off on
every kernel route (wave / affinity / group-serial spread / serial / probe /
preemption) — while every unscheduled pod yields a kube-parity reason whose
per-reason node counts sum to the node count, unknown pods are clean
errors, and records survive a mid-run guard failover with the backend_path
attached.
"""

import copy
import json
import os

import pytest

from open_simulator_tpu.obs import xray
from open_simulator_tpu.resilience import guard
from open_simulator_tpu.simulator.encode import scheduling_signature
from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod

ZONE = "topology.kubernetes.io/zone"


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    xray.disable()
    yield
    xray.disable()


@pytest.fixture()
def recorder(tmp_path):
    rec = xray.enable(str(tmp_path / "trace"))
    yield rec
    xray.disable()


def census_of(sim):
    out = {}
    for i, pods in enumerate(sim.pods_on_node):
        for p in pods:
            key = (i, scheduling_signature(p))
            out[key] = out.get(key, 0) + 1
    return out


def run_pair(nodes, batches, tmp_path, use_waves=True):
    """Schedule the same batches with recording OFF then ON; assert the
    census and failure reasons are bit-identical; return (sim_on, failed_on,
    recorder)."""
    results = []
    for on in (False, True):
        if on:
            rec = xray.enable(str(tmp_path / "trace"))
        sim = Simulator(copy.deepcopy(nodes))
        sim.use_waves = use_waves
        failed = []
        for batch in batches:
            failed.extend(sim.schedule_pods(copy.deepcopy(batch)))
        results.append((sim, failed))
    (sim_off, failed_off), (sim_on, failed_on) = results
    assert census_of(sim_on) == census_of(sim_off)
    assert [u.reason for u in failed_on] == [u.reason for u in failed_off]
    return sim_on, failed_on, rec


def zoned(n, n_zones, **kw):
    return [make_node(f"n{i}", labels={ZONE: f"z{i % n_zones}"}, **kw)
            for i in range(n)]


def replicas(name, n, **kw):
    kw.setdefault("labels", {"app": name})
    return [make_pod(f"{name}-{i}", **kw) for i in range(n)]


def with_spread(pods, app, when="DoNotSchedule", topo=ZONE):
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": topo, "whenUnsatisfiable": when,
            "labelSelector": {"matchLabels": {"app": app}}}]
    return pods


def test_component_names_match_kernel_order():
    # xray.COMPONENT_NAMES is duplicated from kernels.COMPONENT_ORDER so the
    # offline explain path never imports jax; they must never drift
    from open_simulator_tpu.ops.kernels import COMPONENT_ORDER

    assert tuple(xray.COMPONENT_NAMES) == tuple(COMPONENT_ORDER)


# --------------------------------------------------- bit-identity per route ---


def test_wave_route_bit_identical_and_recorded(tmp_path):
    nodes = [make_node(f"n{i}", cpu="8") for i in range(6)]
    sim, _, rec = run_pair(nodes, [replicas("web", 40, cpu="200m")], tmp_path)
    exp = rec.explain("default/web-0")
    assert exp["result_name"] == "scheduled"
    assert exp["segment"]["kind"] == "wave"
    assert exp["node_name"] == sim.na.names[exp["node"]]
    assert exp["node_scores"]["components"]  # per-plugin breakdown present


def test_affinity_route_bit_identical_with_epoch_stats(tmp_path):
    nodes = zoned(8, 4, cpu="8")
    pods = with_spread(replicas("dns", 24, cpu="100m", memory="128Mi"), "dns")
    sim, _, rec = run_pair(nodes, [pods], tmp_path)
    exp = rec.explain("default/dns-3")
    assert exp["segment"]["kind"] == "affinity"
    st = exp["segment"]["stats"]  # the PR 6 fast path is attributable
    assert st["epochs"] >= 1 and st["rounds"] + st["head_fallbacks"] >= 1


def test_spread_route_bit_identical(tmp_path):
    # ScheduleAnyway terms route to the fused group-serial scan
    nodes = zoned(6, 3, cpu="8")
    pods = with_spread(replicas("sa", 20, cpu="100m", memory="128Mi"), "sa",
                       when="ScheduleAnyway")
    _, _, rec = run_pair(nodes, [pods], tmp_path)
    exp = rec.explain("default/sa-0")
    assert exp["segment"]["kind"] == "spread"


def test_serial_route_bit_identical(tmp_path):
    nodes = [make_node(f"n{i}", cpu="8") for i in range(5)]
    pods = [make_pod(f"mix-{i}", cpu=f"{100 + 7 * (i % 9)}m")
            for i in range(30)]  # distinct specs: runs shorter than WAVE_MIN
    _, _, rec = run_pair(nodes, [pods], tmp_path)
    exp = rec.explain("default/mix-11")
    assert exp["segment"]["kind"] == "serial"
    assert exp["result_name"] == "scheduled"


def test_probe_route_bit_identical(tmp_path):
    nodes = [make_node(f"n{i}", cpu="4") for i in range(4)]
    pods = replicas("probe", 30, cpu="900m")

    def probe(on):
        if on:
            xray.enable(str(tmp_path / "trace"))
        sim = Simulator(copy.deepcopy(nodes))
        return sim.probe_pods(copy.deepcopy(pods))

    off = probe(False)
    on = probe(True)
    assert on == off
    # the probe left NO pod rows (probes never materialize placements) but
    # one summary record
    rec = xray.active()
    assert rec.counts()["pods"] == 0
    xray.disable()
    tr = xray.XrayTrace.load(str(tmp_path / "trace"))
    assert tr.probes and tr.probes[0]["scheduled"] == off[0]
    assert tr.probes[0]["total"] == off[1]


def test_preemption_route_bit_identical_with_victim_chain(tmp_path):
    nodes = [make_node("n0", cpu="4")]
    low = replicas("low", 2, cpu="2")
    for p in low:
        p["spec"]["priority"] = 0
    hi = make_pod("hi", cpu="4")
    hi["spec"]["priority"] = 100
    sim, failed, rec = run_pair(nodes, [low + [hi]], tmp_path)
    assert [e["pod"]["metadata"]["name"] for e in sim.preempted] == [
        "low-0", "low-1"]
    exp = rec.explain("default/hi")
    assert exp["result_name"] == "unschedulable"
    assert exp["nominated_node"] == "n0"
    assert exp["victims"] == ["default/low-0", "default/low-1"]
    assert sum(exp["reasons"].values()) == 1  # the one (full) node
    victim = rec.explain("default/low-0")
    assert victim["result_name"] == "preempted"
    assert victim["preempted_by"] == "default/hi"


def test_bound_and_homeless_pods_recorded(tmp_path):
    nodes = [make_node("n0", cpu="8")]
    pods = [make_pod("pinned", node_name="n0"),
            make_pod("lost", node_name="ghost-node"),
            make_pod("free", cpu="100m")]
    _, _, rec = run_pair(nodes, [pods], tmp_path)
    assert rec.explain("default/pinned")["result_name"] == "bound"
    assert rec.explain("default/lost")["result_name"] == "homeless"
    free = rec.explain("default/free")
    assert free["result_name"] == "scheduled"
    # the decision set is attributed to the DISPATCH batch, not the earlier
    # direct-commit batch the bound/homeless rows landed in
    assert free["set_record"]["batch"] == free["batch"]
    assert rec.explain("default/pinned")["batch"] != free["batch"]


# ---------------------------------------------------- reason-count invariant --


def test_every_unscheduled_reason_sums_to_node_count(tmp_path):
    """Mixed fixture: resource exhaustion, taints, unmatched node selector —
    every unscheduled pod's per-reason node counts must sum to N (the kube
    FitError invariant) and its string must render '0/N nodes are
    available'."""
    nodes = ([make_node(f"n{i}", cpu="2") for i in range(4)]
             + [make_node("tainted", cpu="16", taints=[{
                 "key": "dedicated", "value": "infra",
                 "effect": "NoSchedule"}])])
    pods = (replicas("fill", 8, cpu="1")
            + [make_pod("too-big", cpu="64"),
               make_pod("nowhere", cpu="100m",
                        node_selector={"disk": "ssd"}),
               make_pod("both", cpu="64", node_selector={"disk": "ssd"})])
    _, failed, rec = run_pair(nodes, [pods], tmp_path)
    unscheduled = {u.pod["metadata"]["name"] for u in failed}
    assert {"too-big", "nowhere", "both"} <= unscheduled
    n = len(nodes)
    for name in unscheduled:
        exp = rec.explain(f"default/{name}")
        assert exp is not None, name
        reasons = exp["set_record"]["reasons"]
        assert sum(reasons.values()) == n, (name, reasons)
        assert f"0/{n} nodes are available" in exp["reason"]


def test_reasons_reconcile_with_filter_rejection_counters(tmp_path):
    from open_simulator_tpu.obs import REGISTRY

    def rejections():
        out = {}
        prefix = 'simon_filter_rejections_total{reason="'
        for key, val in REGISTRY.values().items():
            if key.startswith(prefix):
                out[key[len(prefix):-2]] = float(val)
        return out

    nodes = [make_node(f"n{i}", cpu="2") for i in range(3)]
    pods = replicas("fill", 4, cpu="1") + [make_pod("big", cpu="64")]
    before = rejections()
    xray.enable(str(tmp_path / "trace"))
    sim = Simulator(copy.deepcopy(nodes))
    sim.schedule_pods(copy.deepcopy(pods))
    delta = {k: int(v - before.get(k, 0.0)) for k, v in rejections().items()
             if v - before.get(k, 0.0)}
    totals = {}
    rec = xray.active()
    exp = rec.explain("default/big")
    for label, count in exp["set_record"]["reasons"].items():
        totals[label] = totals.get(label, 0) + count
    assert totals == delta


# ------------------------------------------------------------- trace queries --


def test_unknown_pod_is_clean_error(tmp_path, capsys):
    nodes = [make_node("n0")]
    _, _, rec = run_pair(nodes, [[make_pod("real")]], tmp_path)
    assert rec.explain("default/ghost") is None
    xray.disable()
    from open_simulator_tpu.cli.main import main

    rc = main(["explain", "default/ghost",
               "--trace", str(tmp_path / "trace")])
    assert rc == 1
    assert "no decision record" in capsys.readouterr().err
    rc = main(["explain", "missing", "--trace", str(tmp_path / "nothere")])
    assert rc == 1


def test_trace_round_trip_matches_in_memory(tmp_path):
    nodes = zoned(6, 3, cpu="4")
    pods = (with_spread(replicas("dns", 12, cpu="100m"), "dns")
            + [make_pod("big", cpu="64")])
    _, _, rec = run_pair(nodes, [pods], tmp_path)
    mem = rec.explain("default/dns-0")
    xray.disable()
    tr = xray.XrayTrace.load(str(tmp_path / "trace"))
    disk = tr.explain("default/dns-0")
    assert disk["node_name"] == mem["node_name"]
    assert disk["segment"] == mem["segment"]
    assert disk["set_record"] == mem["set_record"]
    assert disk["node_scores"] == mem["node_scores"]  # via the npz sidecar
    assert os.path.exists(str(tmp_path / "trace.npz"))
    # the unscheduled summary survives the round trip too
    assert ({r["pod"] for r in tr.unscheduled_summary()}
            == {"default/big"})
    # bare-name lookup resolves when unambiguous
    assert tr.explain("big")["result_name"] == "unschedulable"


def test_explain_cli_renders_kube_parity_event(tmp_path, capsys):
    nodes = [make_node("n0", cpu="2")]
    _, _, _rec = run_pair(nodes, [[make_pod("huge", cpu="64")]], tmp_path)
    xray.disable()
    from open_simulator_tpu.cli.main import main

    rc = main(["explain", "default/huge", "--trace", str(tmp_path / "trace")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "FailedScheduling: 0/1 nodes are available: 1 Insufficient cpu." in out
    rc = main(["explain", "--unscheduled", "--trace",
               str(tmp_path / "trace")])
    assert rc == 0
    assert "default/huge" in capsys.readouterr().out


# -------------------------------------------------------- failover survival ---


def test_recording_survives_guard_failover(tmp_path):
    """A watchdog wedge mid-run fails over to the CPU fallback and replays;
    the committed records must be the REPLAY's (no phantom rows from the
    rolled-back attempt) and must carry the full backend_path."""
    from open_simulator_tpu.resilience import FaultPlan, install_plan, clear_plan
    from open_simulator_tpu.resilience.faults import FaultSpec

    guard.reset_for_tests()
    nodes = [make_node(f"n{i}", cpu="8") for i in range(4)]
    pods = replicas("fo", 12, cpu="200m")
    xray.enable(str(tmp_path / "trace"))
    try:
        install_plan(FaultPlan([FaultSpec("watchdog_wedge", 1)]))
        sim = Simulator(copy.deepcopy(nodes))
        failed = sim.schedule_pods(copy.deepcopy(pods))
    finally:
        clear_plan()
        guard.reset_for_tests()
    assert not failed
    assert sim.backend_path.count("cpu") >= 2  # initial + failover
    rec = xray.active()
    assert rec.counts()["pods"] == len(pods)  # exactly one row per pod
    exp = rec.explain("default/fo-0")
    assert exp["backend_path"] == sim.backend_path
    assert exp["result_name"] == "scheduled"


# ----------------------------------------------------------- server surface ---


def test_server_explain_endpoint(tmp_path):
    import http.client
    import threading

    from open_simulator_tpu.core.types import ResourceTypes
    from open_simulator_tpu.server.http import ClusterSnapshot, Server

    snap = ClusterSnapshot(
        ResourceTypes(nodes=[make_node("n1", cpu="8")]), [], [], [])
    server = Server(snapshot_fn=lambda: snap, xray=True)
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        body = {"pods": [make_pod("api-0", cpu="100m"),
                         make_pod("whale", cpu="900")]}
        conn.request("POST", "/api/deploy-apps", json.dumps(body),
                     {"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        conn.request("GET", "/explain/default/whale")
        resp = conn.getresponse()
        assert resp.status == 200
        doc = json.loads(resp.read())
        assert "FailedScheduling" in doc["rendered"]
        assert doc["explanation"]["result_name"] == "unschedulable"
        conn.request("GET", "/explain/default/ghost")
        resp = conn.getresponse()
        assert resp.status == 404
        assert "no decision record" in json.loads(resp.read())["error"]
        conn.request("GET", "/debug/vars")
        doc = json.loads(conn.getresponse().read())
        assert doc["xray"]["pods"] >= 2
        assert doc["xray"]["unscheduled"] >= 1  # the total count survives
        assert any(r["pod"] == "default/whale"
                   for r in doc["xray"]["unscheduled_sample"])
    finally:
        httpd.shutdown()


def test_server_explain_404_when_xray_off():
    import http.client
    import threading

    from open_simulator_tpu.core.types import ResourceTypes
    from open_simulator_tpu.server.http import ClusterSnapshot, Server

    snap = ClusterSnapshot(ResourceTypes(nodes=[make_node("n1")]), [], [], [])
    server = Server(snapshot_fn=lambda: snap, xray=False)
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/explain/default/x")
        resp = conn.getresponse()
        assert resp.status == 404
        assert "xray recording is off" in json.loads(resp.read())["error"]
    finally:
        httpd.shutdown()


# ------------------------------------------------------------ chrome / spans --


def test_schedule_run_span_carries_decision_records(tmp_path, recorder):
    from open_simulator_tpu.obs.chrome import chrome_trace
    from open_simulator_tpu.utils.trace import start_collection, stop_collection

    nodes = zoned(6, 3, cpu="8")
    pods = with_spread(replicas("dns", 16, cpu="100m"), "dns")
    start_collection()
    sim = Simulator(copy.deepcopy(nodes))
    sim.schedule_pods(copy.deepcopy(pods))
    spans = stop_collection()
    runs = [s for s in spans if s.name == "schedule_run"]
    assert runs and "xray" in runs[0].meta
    meta = runs[0].meta["xray"]
    assert meta["pods"] == len(pods)
    assert meta["segments"][0]["kind"] == "affinity"
    assert "stats" in meta["segments"][0]  # epoch attribution rides along
    # the Chrome export carries it as event args + the affinity step events
    doc = chrome_trace(spans)
    ev = next(e for e in doc["traceEvents"]
              if e["name"] == "schedule_run" and e["args"].get("xray"))
    assert ev["args"]["xray"]["decision_sets"] >= 1
    assert any(e["name"].startswith("affinity[")
               for e in doc["traceEvents"] if e["cat"] == "step")


# -------------------------------------------------------------- metrics diff --


def test_metrics_diff_flags_regressions(tmp_path, capsys):
    a = {"simon_commits_total": {
            "type": "counter", "help": "", "label_names": [],
            "samples": [{"labels": {}, "value": 10}]},
         "simon_compile_cache_misses_total": {
            "type": "counter", "help": "", "label_names": ["kernel", "shape"],
            "samples": [{"labels": {"kernel": "k", "shape": "s"},
                         "value": 0}]}}
    b = copy.deepcopy(a)
    b["simon_commits_total"]["samples"][0]["value"] = 12
    b["simon_compile_cache_misses_total"]["samples"][0]["value"] = 3
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    from open_simulator_tpu.cli.main import main

    rc = main(["metrics", "--diff", str(pa), str(pb)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "simon_commits_total  10 -> 12  (+2)" in out
    assert "REGRESSION" in out
    assert "2 metric(s) changed, 1 regression(s)" in out
    rc = main(["metrics", "--diff", "--fail-on-regression",
               str(pa), str(pb)])
    capsys.readouterr()
    assert rc == 1
    # reversed direction: the miss counter going backwards is flagged too
    rc = main(["metrics", "--diff", str(pb), str(pa)])
    out = capsys.readouterr().out
    assert rc == 0 and "counter went backwards" in out


# ------------------------------------------------------------- zero-cost off --


def test_recording_off_adds_no_dispatch_signatures():
    """With recording off the engine must not touch the recorder, move xray
    counters, or register explain/stats dispatch signatures — the
    byte-identical-metrics half of the zero-cost gate (delta-checked: the
    process registry may carry counters from earlier recorded tests)."""
    from open_simulator_tpu.obs import REGISTRY

    def slice_of(v):
        return {k: x for k, x in v.items()
                if "xray" in k or "explain_pod" in k or "stats=True" in k}

    before = slice_of(REGISTRY.values())
    nodes = [make_node(f"n{i}", cpu="8") for i in range(4)]
    sim = Simulator(copy.deepcopy(nodes))
    sim.schedule_pods([make_pod(f"z-{i}", cpu="100m") for i in range(12)])
    assert slice_of(REGISTRY.values()) == before
    assert sim._xray_run is None
