"""Mesh-sharded scheduling must place pods identically to the single-device path."""

import numpy as np
import jax.numpy as jnp

from open_simulator_tpu.ops import kernels
from open_simulator_tpu.parallel import (
    make_node_mesh,
    pad_batch_tables,
    schedule_batch_on_mesh,
    schedule_scenarios_on_mesh,
)
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.utils.synth import synth_cluster


def _encode(n_nodes, n_pods, hard=True):
    nodes, pods = synth_cluster(n_nodes, n_pods, hard_predicates=hard)
    sim = Simulator(nodes)
    return sim, sim.encode_batch(pods)


def _run_single(sim, bt):
    tables, carry = sim._to_device(bt)
    _, choices = kernels.schedule_batch(
        tables, carry, jnp.asarray(bt.pod_group), jnp.asarray(bt.forced_node),
        jnp.asarray(bt.valid), n_zones=bt.n_zones,
    )
    return np.asarray(choices)


def test_sharded_matches_single_device():
    sim, bt = _encode(26, 48)  # 26 nodes: not divisible by 8 → exercises padding
    want = _run_single(sim, bt)
    mesh = make_node_mesh(8)
    _, got = schedule_batch_on_mesh(bt, mesh)
    np.testing.assert_array_equal(want, np.asarray(got))


def test_sharded_simple_cluster():
    sim, bt = _encode(16, 32, hard=False)
    want = _run_single(sim, bt)
    _, got = schedule_batch_on_mesh(bt, make_node_mesh(4))
    np.testing.assert_array_equal(want, np.asarray(got))


def test_padding_never_placed():
    sim, bt = _encode(10, 20)
    padded = pad_batch_tables(bt, 8)
    assert padded.alloc.shape[0] == 16
    assert not padded.static_mask[:, 10:].any()
    _, choices = schedule_batch_on_mesh(bt, make_node_mesh(8))
    ch = np.asarray(choices)
    assert ch.max() < 10
    # padding must not perturb score normalizers / zone sums: exact placement parity
    np.testing.assert_array_equal(_run_single(sim, bt), ch)


def test_scenarios_dp_axis():
    sim, bt = _encode(16, 24)
    mesh = make_node_mesh(8, scenario_axis=2)
    padded = pad_batch_tables(bt, mesh.shape["nodes"])
    n_pad, R = padded.seed_requested.shape
    seeds = np.zeros((2, n_pad, R), np.float32)
    # scenario 1 starts half-utilized → placements may differ but shapes must hold
    seeds[1] = padded.alloc * 0.5
    choices = np.asarray(schedule_scenarios_on_mesh(bt, mesh, seeds))
    assert choices.shape == (2, bt.pod_group.shape[0])
    # scenario 0 (empty cluster) must equal the plain single-device run
    want = _run_single(sim, bt)
    np.testing.assert_array_equal(want, choices[0])


def test_engine_mesh_product_path_matches_single_device():
    """The PRODUCT path (Simulator(use_mesh=True) -> _to_device shards over all
    visible devices) must place identically to the single-device engine on a
    mixed workload: waves, spread group-serial, and serial segments."""
    import copy

    from open_simulator_tpu.simulator.engine import Simulator

    from fixtures import make_node, make_pod

    nodes = []
    for z in range(4):
        for i in range(4):
            nodes.append(make_node(f"z{z}-n{i}", cpu="8", memory="16Gi",
                                   labels={"zone": f"z{z}"}))
    pods = [make_pod(f"web-{i}", cpu="250m", memory="256Mi",
                     labels={"app": "web"}) for i in range(40)]
    for i in range(16):
        p = make_pod(f"spread-{i}", cpu="250m", memory="256Mi",
                     labels={"app": "spread"})
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "spread"}},
        }]
        pods.append(p)
    pods += [make_pod(f"porty-{i}", cpu="250m", memory="256Mi",
                      labels={"app": "porty"}, host_ports=[9090])
             for i in range(3)]

    results = []
    for use_mesh in (True, False):
        sim = Simulator(copy.deepcopy(nodes), use_mesh=use_mesh)
        failed = sim.schedule_pods(copy.deepcopy(pods))
        census = {}
        for i, nodepods in enumerate(sim.pods_on_node):
            for p in nodepods:
                key = (i, p["metadata"]["labels"]["app"])
                census[key] = census.get(key, 0) + 1
        results.append((census, len(failed)))
    assert results[0] == results[1]


def test_global_mesh_axes_and_scenarios():
    """distributed.make_global_mesh: (scenarios, nodes) over all devices;
    scenario slices stay contiguous (the DCN axis when multi-process)."""
    import jax

    from open_simulator_tpu.parallel.distributed import (
        initialize,
        make_global_mesh,
        node_mesh_local,
    )

    assert initialize() is False  # single-process: a documented no-op
    mesh = make_global_mesh(scenario_axis=2)
    assert mesh.axis_names == ("scenarios", "nodes")
    assert mesh.shape["scenarios"] == 2
    assert mesh.shape["nodes"] == len(jax.devices()) // 2
    local = node_mesh_local()
    assert local.axis_names == ("nodes",)

    # and it drives the DP scenario path end to end
    import numpy as np

    sim, bt = _encode(16, 24)
    from open_simulator_tpu.parallel import pad_batch_tables, schedule_scenarios_on_mesh

    bt2 = pad_batch_tables(bt, mesh.shape["nodes"])
    S = 2
    seeds = np.zeros((S, bt2.seed_requested.shape[0], bt2.seed_requested.shape[1]),
                     np.float32)
    choices = schedule_scenarios_on_mesh(bt2, mesh, seeds)
    assert np.asarray(choices).shape[0] == S


def test_engine_mesh_epoch_spread_wave_matches_single_device(monkeypatch):
    """The epoch-batched spread wave (high-cardinality hostname spread) under
    the 8-way node mesh must place identically to single-device."""
    import copy

    # pin the routing threshold so an ambient tuning can't skip the epoch wave
    monkeypatch.delenv("OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS", raising=False)

    from open_simulator_tpu.simulator.encode import scheduling_signature
    from fixtures import make_node, make_pod

    nodes = [make_node(f"m{i}", pods="6") for i in range(96)]
    pods = []
    for i in range(200):
        p = make_pod(f"sp-{i}", cpu="50m", memory="64Mi", labels={"app": "sp"})
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 2, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "sp"}}}]
        pods.append(p)

    def census(sim):
        out = {}
        for i, nps in enumerate(sim.pods_on_node):
            for p in nps:
                k = (i, scheduling_signature(p))
                out[k] = out.get(k, 0) + 1
        return out

    sim_mesh = Simulator(copy.deepcopy(nodes), use_mesh=True)
    f1 = sim_mesh.schedule_pods(copy.deepcopy(pods))
    assert sim_mesh._wave_eligibility(0).kind == "affinity"  # epoch wave routed
    sim_single = Simulator(copy.deepcopy(nodes), use_mesh=False)
    f2 = sim_single.schedule_pods(copy.deepcopy(pods))
    assert census(sim_mesh) == census(sim_single)
    assert len(f1) == len(f2) == 0


def test_probe_fanout_scenario_mesh_matches_local():
    """The capacity prober's multi-candidate fan-out: S node-active masks in
    one dispatch must equal S independently masked schedule_batch runs, both
    on the local vmap path and sharded over a pure-scenario mesh."""
    from open_simulator_tpu.parallel import make_scenario_mesh, put_fanout_inputs

    sim, bt = _encode(12, 24, hard=False)
    tables, carry = sim._to_device(bt)
    N = bt.alloc.shape[0]
    S = 4
    counts = (3, 6, 9, 12)
    active = np.zeros((S, N), bool)
    for i, n in enumerate(counts):
        active[i, :n] = True

    # per-lane reference: schedule_batch with the mask folded into static_mask
    want = []
    for i in range(S):
        tb2 = tables._replace(
            static_mask=tables.static_mask & jnp.asarray(active[i])[None, :])
        _, ch = kernels.schedule_batch(
            tb2, carry, jnp.asarray(bt.pod_group), jnp.asarray(bt.forced_node),
            jnp.asarray(bt.valid), n_zones=bt.n_zones)
        want.append(int(np.asarray(jnp.sum((ch >= 0).astype(jnp.int32)))))

    carry_s = kernels.Carry(*(jnp.broadcast_to(v, (S,) + v.shape) for v in carry))
    _, placed_local = kernels.probe_serial_fanout(
        tables, carry_s, jnp.asarray(active), jnp.asarray(bt.pod_group),
        jnp.asarray(bt.forced_node), jnp.asarray(bt.valid), n_zones=bt.n_zones)
    assert np.asarray(placed_local).tolist() == want

    # one candidate lane per device on the ('scenarios', 'nodes'=1) mesh
    mesh = make_scenario_mesh(4)
    assert mesh.shape["scenarios"] == 4 and mesh.shape["nodes"] == 1
    seeds = (bt.seed_requested, bt.seed_nonzero, bt.seed_port_used,
             bt.seed_counter, bt.seed_carrier, bt.seed_dev_used,
             bt.seed_vg_req, bt.seed_sdev_alloc)
    carry_np = tuple(np.broadcast_to(a, (S,) + a.shape) for a in seeds)
    tables_m, carry_m, active_m = put_fanout_inputs(mesh, bt, carry_np, active)
    with mesh:
        _, placed_mesh = kernels.probe_serial_fanout(
            tables_m, carry_m, active_m, jnp.asarray(bt.pod_group),
            jnp.asarray(bt.forced_node), jnp.asarray(bt.valid),
            n_zones=bt.n_zones)
    assert np.asarray(placed_mesh).tolist() == want
