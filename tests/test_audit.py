"""simonaudit tests: the HLO parsers, certificate extraction on real
kernels, regression detection against goldens, the wave-chain boundary
invariant, and the CI negative control (doctored fixture golden MUST fail).

The heavyweight full-matrix check (every kernel x bucket x mesh, ~1-2 min of
CPU compiles) is slow-marked; CI runs it via `python tools/run_audit.py`."""

import copy
import json
from pathlib import Path

import pytest

from open_simulator_tpu.analysis import hlo
from open_simulator_tpu.analysis.rules import _DISPATCH_KERNELS
from open_simulator_tpu.ops import kernels

GOLDEN = Path(__file__).parent / "golden" / "audit"
GOLDEN_FIXTURE = Path(__file__).parent / "golden" / "audit_fixture"


# ------------------------------------------------------------ registry ----


def test_registry_covers_every_dispatch_kernel():
    """The audit registry and simonlint's naked-dispatch kernel set must
    name the SAME hot kernels: a kernel the watchdog guards is a kernel the
    auditor certifies."""
    assert set(kernels.HOT_KERNELS) == set(_DISPATCH_KERNELS)


def test_every_registered_kernel_has_a_golden():
    for name in list(kernels.HOT_KERNELS) + [hlo.CHAIN_TARGET]:
        doc = hlo.load_golden(str(GOLDEN), name)
        assert doc is not None, f"no golden certificate file for {name}"
        # every kernel is certified at >= 2 mesh shapes per bucket
        meshes = {k.split("/")[1] for k in doc["certs"]}
        assert len(meshes) >= 2 or name == hlo.CHAIN_TARGET, (name, meshes)


# --------------------------------------------------------- HLO parsers ----

_FAKE_HLO = (
    'HloModule jit_k, is_scheduled=true, input_output_alias={ {0}: (31, {}, '
    'may-alias), {1}: (32, {}, may-alias) }, entry_computation_layout=...\n'
    '  %ar = f32[4,8]{1,0} all-reduce(%x), replica_groups={}\n'
    '  %ags = (f32[2,4]{1,0}, f32[4,4]{1,0}) all-gather-start(%y)\n'
    '  %agd = f32[4,4]{1,0} all-gather-done(%ags)\n'
    '  %use = f32[4,8]{1,0} add(%ar, %ar)\n'
    '  %cc = f32[1]{0} custom-call(%use), custom_call_target="TopK"\n'
    '  %cb = f32[1]{0} custom-call(%use), '
    'custom_call_target="xla_python_cpu_callback"\n'
)


def test_collective_census_counts_and_bytes():
    census = hlo.collective_census(_FAKE_HLO)
    assert census["all-reduce"] == {"count": 1, "bytes": 4 * 8 * 4}
    # -start counted once (tuple bytes summed), -done not double-counted
    assert census["all-gather"]["count"] == 1
    assert census["all-gather"]["bytes"] == (2 * 4 + 4 * 4) * 4
    assert "all-to-all" not in census


def test_alias_count_balances_nested_braces():
    assert hlo._alias_count(_FAKE_HLO) == 2
    assert hlo._alias_count("HloModule jit_k, entry_computation_layout=x\n") == 0


def test_escape_census_splits_host_callbacks():
    custom, host = hlo.escape_census(_FAKE_HLO)
    assert custom == ["TopK"]
    assert host == ["xla_python_cpu_callback"]


# ------------------------------------------------- live certificates ----


def test_schedule_wave_certificate_matches_golden():
    cert = hlo.audit_kernel("schedule_wave", "s16x32", 2)
    assert cert["collective_count"] > 0  # the wave genuinely reduces
    assert cert["donation"] == {"declared": 8, "aliased": 8, "held": True,
                                "image_leaf_aliased": 0}
    assert cert["host_callbacks"] == []
    assert cert["carry_promotions"] == []
    golden = hlo.load_golden(str(GOLDEN), "schedule_wave")
    gcert = golden["certs"]["s16x32/nodes2"]
    assert hlo.check_cert(cert, gcert) == []
    assert cert["static_digest"] == gcert["static_digest"]


def test_single_device_certificate_has_no_collectives():
    cert = hlo.audit_kernel("schedule_wave", "s16x32", 1)
    assert cert["collectives"] == {}
    assert cert["donation"]["held"]


def test_diagnostics_kernels_never_donate():
    cert = hlo.audit_kernel("feasibility_jit", "s16x32", 1)
    assert cert["donation"]["declared"] == 0
    assert cert["carry_promotions"] == []


def test_wave_chain_boundary_inserts_nothing_and_donation_holds():
    """The acceptance invariant: the mesh8 wave-chain certificate
    independently confirms zero boundary collectives (the static proof
    behind reshard_bytes == 0) with the chained carry still donated."""
    cert = hlo.audit_wave_chain("s16x32", 8)
    assert cert["boundary_collectives"] == 0
    assert cert["collective_count"] == 2 * cert["single_collective_count"]
    assert cert["donation"]["held"]
    golden = hlo.load_golden(str(GOLDEN), hlo.CHAIN_TARGET)
    assert hlo.check_cert(cert, golden["certs"]["s16x32/nodes8"]) == []


# ------------------------------------------------- regression gating ----


def _golden_cert():
    return copy.deepcopy(
        hlo.load_golden(str(GOLDEN), "schedule_wave")["certs"]["s16x32/nodes8"])


@pytest.mark.parametrize("mutate,needle", [
    (lambda c: c["collectives"].setdefault(
        "all-to-all", {"count": 1, "bytes": 64}), "NEW collective kind"),
    (lambda c: c["collectives"]["all-reduce"].__setitem__(
        "count", c["collectives"]["all-reduce"]["count"] + 1), "count grew"),
    (lambda c: c.__setitem__("static_digest", "0" * 16), "signature drift"),
    (lambda c: c["donation"].update(aliased=3, held=False),
     "donation dropped"),
    (lambda c: c.__setitem__("host_callbacks", ["xla_python_cpu_callback"]),
     "host callbacks escape"),
    (lambda c: c.__setitem__("carry_promotions",
                             [{"leaf": "requested", "in": "float32",
                               "out": "float64"}]), "dtype promotion"),
])
def test_check_cert_flags_each_regression_class(mutate, needle):
    golden = _golden_cert()
    live = copy.deepcopy(golden)
    mutate(live)
    live["collective_count"] = sum(
        v["count"] for v in live["collectives"].values())
    msgs = hlo.check_cert(live, golden)
    assert any(needle in m for m in msgs), msgs


def test_check_cert_clean_on_identical():
    golden = _golden_cert()
    assert hlo.check_cert(copy.deepcopy(golden), golden) == []


def test_missing_golden_is_a_regression(tmp_path):
    cert = _golden_cert()
    regressions, _ = hlo.check_certs([cert], str(tmp_path))
    assert regressions and "no golden certificate" in regressions[0]


def test_fixture_gate_fails_against_doctored_golden():
    """The CI negative control: the deliberately-regressing fixture kernel
    (one extra all-reduce vs its checked-in golden) MUST fail --check."""
    cert = hlo.audit_fixture(8)
    assert cert["collectives"]["all-reduce"]["count"] == 2
    regressions, _ = hlo.check_certs([cert], str(GOLDEN_FIXTURE))
    assert any("all-reduce count grew 1 -> 2" in r for r in regressions)
    assert any("exceeds budget" in r for r in regressions)


# --------------------------------------------------------------- CLI ----


def test_cli_rejects_unknown_targets_and_buckets():
    with pytest.raises(SystemExit):
        hlo.run_audit(["--select", "no-such-kernel"])
    with pytest.raises(SystemExit):
        hlo.run_audit(["--buckets", "no-such-bucket"])


def test_cli_check_fixture_exit_codes(capsys):
    rc = hlo.run_audit(["--check", "--select", hlo.FIXTURE_TARGET,
                        "--golden-dir", str(GOLDEN_FIXTURE)])
    assert rc == 1
    out = capsys.readouterr()
    assert "REGRESSION" in out.err


def test_update_roundtrip_is_stable(tmp_path):
    """--update into a fresh dir, then check against it: zero regressions
    and a byte-identical second write (the digest is deterministic)."""
    cert = hlo.audit_kernel("schedule_wave", "s16x32", 2)
    hlo.write_goldens(str(tmp_path), [cert])
    first = (tmp_path / "schedule_wave.json").read_text()
    cert2 = hlo.audit_kernel("schedule_wave", "s16x32", 2)
    regressions, notes = hlo.check_certs([cert2], str(tmp_path))
    assert regressions == []
    hlo.write_goldens(str(tmp_path), [cert2])
    assert (tmp_path / "schedule_wave.json").read_text() == first
    assert json.loads(first)["certs"]["s16x32/nodes2"]["schema"] == hlo.SCHEMA


@pytest.mark.slow
def test_full_matrix_matches_goldens():
    """Every registered hot kernel at every canonical bucket x mesh shape
    agrees with its golden certificate (the CI gate, in-process)."""
    certs = hlo.run_targets(None, hlo.DEFAULT_BUCKETS, hlo.DEFAULT_SHARDS)
    assert len(certs) == len(kernels.HOT_KERNELS) * 2 * 3 + 2
    regressions, _ = hlo.check_certs(certs, str(GOLDEN))
    assert regressions == [], "\n".join(regressions)


def test_lowerable_rejects_stats_on_non_affinity_kernels():
    from open_simulator_tpu.parallel.mesh import make_node_mesh, sharded_kernels

    sk = sharded_kernels(make_node_mesh(1))
    with pytest.raises(ValueError, match="no stats variant"):
        sk.lowerable("schedule_wave", stats=True)


def test_selected_chain_without_multishard_mesh_is_an_error():
    # the chain target needs a multi-shard mesh; selecting it with only
    # 1-shard meshes must refuse loudly, never silently skip the target
    # (alone OR alongside other targets) and report a green gate
    with pytest.raises(SystemExit):
        hlo.run_audit(["--check", "--select", hlo.CHAIN_TARGET,
                       "--shards", "1"])
    with pytest.raises(SystemExit):
        hlo.run_audit(["--check", "--shards", "1",
                       "--select", f"{hlo.CHAIN_TARGET},schedule_wave"])


def test_full_update_prunes_stale_goldens(tmp_path):
    stale = {"schema": hlo.SCHEMA, "kernel": "removed_kernel", "certs": {}}
    (tmp_path / "removed_kernel.json").write_text(json.dumps(stale))
    cert = hlo.audit_fixture(8)
    live = copy.deepcopy(cert)
    live["mesh"] = "nodes2"  # a mesh key no longer produced
    hlo.write_goldens(str(tmp_path), [live])
    # partial write merges; full write regenerates and prunes
    hlo.write_goldens(str(tmp_path), [cert], full=True)
    assert not (tmp_path / "removed_kernel.json").exists()
    doc = json.loads((tmp_path / f"{hlo.FIXTURE_TARGET}.json").read_text())
    assert list(doc["certs"]) == ["fixture/nodes8"]  # stale key dropped


def test_update_preserves_hand_tightened_budgets(tmp_path):
    """--update must never silently loosen a pinned golden budget: the
    stricter bound and the hand-written note survive regeneration, and only
    a hand edit of the golden file can relax them."""
    cert = hlo.audit_fixture(8)
    hlo.write_goldens(str(tmp_path), [cert])
    doc = json.loads((tmp_path / f"{hlo.FIXTURE_TARGET}.json").read_text())
    key = "fixture/nodes8"
    doc["certs"][key]["budget"]["max_collective_count"] = 1  # hand-tightened
    doc["certs"][key]["budget"]["note"] = "pinned: one reduction only"
    (tmp_path / f"{hlo.FIXTURE_TARGET}.json").write_text(json.dumps(doc))
    hlo.write_goldens(str(tmp_path), [hlo.audit_fixture(8)], full=True)
    after = json.loads((tmp_path / f"{hlo.FIXTURE_TARGET}.json").read_text())
    assert after["certs"][key]["budget"]["max_collective_count"] == 1
    assert after["certs"][key]["budget"]["note"] == "pinned: one reduction only"
