"""CLI command tree + HTTP server mode."""

import http.client
import json
import os
import sys
import threading

import pytest

from open_simulator_tpu.cli.main import main as cli_main
from open_simulator_tpu.core.types import ResourceTypes
from open_simulator_tpu.server.http import ClusterSnapshot, Server

from fixtures import make_deployment, make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------- CLI ---------


def test_cli_version(capsys):
    assert cli_main(["version"]) == 0
    out = capsys.readouterr().out
    assert "Version:" in out and "Commit:" in out


def test_cli_gen_doc(tmp_path):
    assert cli_main(["gen-doc", "-d", str(tmp_path)]) == 0
    files = {f.name for f in tmp_path.iterdir()}
    assert {"simon.md", "simon_apply.md", "simon_server.md", "simon_version.md"} <= files
    assert "--simon-config" in (tmp_path / "simon_apply.md").read_text()


def test_cli_gen_doc_bad_dir(capsys):
    assert cli_main(["gen-doc", "-d", "/nonexistent/dir"]) == 1


def test_cli_apply_runs_example(tmp_path, monkeypatch):
    monkeypatch.chdir(REPO)
    out = tmp_path / "report.txt"
    rc = cli_main([
        "apply", "-f", "examples/simon-smoke-config.yaml", "--output-file", str(out),
        "--use-greed",
    ])
    assert rc == 0
    assert "Simulation success!" in out.read_text()


def test_cli_apply_profile_writes_device_trace(tmp_path, monkeypatch):
    # --profile DIR wraps the run in jax.profiler.trace and must leave a
    # trace artifact behind (the pprof/debug surfaces are tested in
    # test_trace.py; this covers the CLI flag wiring end to end)
    monkeypatch.chdir(REPO)
    trace_dir = tmp_path / "trace"
    rc = cli_main([
        "apply", "-f", "examples/simon-smoke-config.yaml",
        "--profile", str(trace_dir),
    ])
    assert rc == 0
    dumped = [p for p in trace_dir.rglob("*") if p.is_file()]
    assert dumped, "expected jax.profiler trace files under --profile DIR"


def test_cli_apply_missing_config(capsys):
    assert cli_main(["apply", "-f", "/nonexistent.yaml"]) == 1
    assert "apply error" in capsys.readouterr().err


def test_cli_apply_fault_plan_fails_cleanly(tmp_path, monkeypatch, capsys):
    """--fault-plan injects deterministically: the run fails with the
    injected site in the error, prints the replayable trace, and clears the
    plan for later runs in the same process."""
    from open_simulator_tpu.resilience import active_plan

    monkeypatch.chdir(REPO)
    rc = cli_main([
        "apply", "-f", "examples/simon-smoke-config.yaml",
        "--output-file", str(tmp_path / "report.txt"),
        "--fault-plan", "site=encode,attempt=1",
    ])
    err = capsys.readouterr().err
    assert rc == 1
    assert "apply error" in err and "injected fault at encode" in err
    assert 'fault plan trace: [["encode", 1, "runtime"]]' in err
    assert active_plan() is None  # cleared even on failure


def test_cli_apply_deadline_expires_cleanly(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    rc = cli_main([
        "apply", "-f", "examples/simon-smoke-config.yaml",
        "--output-file", str(tmp_path / "report.txt"),
        "--deadline", "0.0001",
    ])
    assert rc == 1
    assert "deadline exceeded" in capsys.readouterr().err


def test_cli_apply_trace_and_metrics_out(tmp_path, monkeypatch):
    """--trace-out writes a perfetto-loadable Chrome trace with nested engine
    spans and the metrics snapshot; --metrics-out writes the snapshot alone;
    `simon metrics` renders either as Prometheus text."""
    monkeypatch.chdir(REPO)
    trace_f = tmp_path / "trace.json"
    metrics_f = tmp_path / "metrics.json"
    rc = cli_main([
        "apply", "-f", "examples/simon-smoke-config.yaml",
        "--output-file", str(tmp_path / "report.txt"),
        "--trace-out", str(trace_f), "--metrics-out", str(metrics_f),
    ])
    assert rc == 0
    doc = json.loads(trace_f.read_text())  # valid JSON end to end
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    names = {e["name"] for e in evs}
    assert "Simulate" in names and "schedule_run" in names  # nested engine spans
    assert all(e.get("ph") == "X" and "ts" in e and "dur" in e for e in evs)
    snap = json.loads(metrics_f.read_text())
    assert "simon_scheduling_attempts_total" in snap
    assert doc["metadata"]["metrics"].keys() == snap.keys()


def test_cli_metrics_renders_snapshot(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    metrics_f = tmp_path / "metrics.json"
    assert cli_main([
        "apply", "-f", "examples/simon-smoke-config.yaml",
        "--output-file", str(tmp_path / "report.txt"),
        "--metrics-out", str(metrics_f),
    ]) == 0
    capsys.readouterr()
    assert cli_main(["metrics", str(metrics_f)]) == 0
    out = capsys.readouterr().out
    assert "# TYPE simon_scheduling_attempts_total counter" in out
    assert "simon_commits_total" in out
    assert cli_main(["metrics", "/nonexistent.json"]) == 1
    # a trace file WITHOUT an embedded snapshot is an error, not silent success
    bare = tmp_path / "bare_trace.json"
    bare.write_text('{"traceEvents": []}')
    assert cli_main(["metrics", str(bare)]) == 1
    assert "no metrics snapshot" in capsys.readouterr().err


# -------------------------------------------------------------------- server --------


def _snapshot(nodes=None, pods=None, rs=None, pending=None):
    rt = ResourceTypes(nodes=nodes or [], pods=pods or [])
    return ClusterSnapshot(rt, rs or [], [], pending or [])


def test_deploy_apps_handler():
    nodes = [make_node("n1"), make_node("n2")]
    server = Server(snapshot_fn=lambda: _snapshot(nodes=nodes))
    deploy = make_deployment("web", replicas=3, cpu="1", memory="1Gi")
    code, body = server.handle_deploy_apps({"deployments": [deploy]})
    assert code == 200
    assert body["unscheduledPods"] == []
    placed = sum(len(ns["pods"]) for ns in body["nodeStatus"])
    assert placed == 3


def test_deploy_apps_does_not_mutate_shared_snapshot():
    # an injectable snapshot_fn may hand back shared lists; fake nodes must not
    # accumulate across requests
    snap = _snapshot(nodes=[make_node("n1")])
    server = Server(snapshot_fn=lambda: snap)
    newnode = make_node("template")
    for _ in range(3):
        code, _body = server.handle_deploy_apps({"newnodes": [newnode]})
        assert code == 200
    assert len(snap.resource.nodes) == 1


def test_deploy_apps_newnodes_and_pending():
    pending = [make_pod("stuck", cpu="1", memory="1Gi")]
    server = Server(snapshot_fn=lambda: _snapshot(nodes=[], pending=pending))
    new_node = make_node("fresh", cpu="8", memory="16Gi")
    code, body = server.handle_deploy_apps({"newnodes": [new_node]})
    assert code == 200
    # the pending pod has no app label → filtered from nodeStatus, but scheduled
    assert body["unscheduledPods"] == []


def test_deploy_apps_busy_returns_503():
    server = Server(snapshot_fn=lambda: _snapshot(nodes=[make_node("n1")]))
    server.deploy_lock.acquire()
    try:
        code, body = server.handle_deploy_apps({})
        # structured error contract: {"error": ..., "code": ...}
        assert code == 503 and "busy" in body["error"] and body["code"] == 503
    finally:
        server.deploy_lock.release()
    # the busy path never released a lock it didn't hold: the endpoint
    # works again immediately
    code, _body = server.handle_deploy_apps({})
    assert code == 200


def test_scale_apps_removes_owned_pods():
    """Scaling a deployment replaces its existing pods with the new replica count."""
    nodes = [make_node("n1")]
    rs = {"apiVersion": "apps/v1", "kind": "ReplicaSet",
          "metadata": {"name": "web-abc", "namespace": "default",
                       "ownerReferences": [{"kind": "Deployment", "name": "web"}]}}
    old_pods = []
    for i in range(2):
        p = make_pod(f"web-abc-{i}", cpu="1", memory="1Gi", node_name="n1")
        p["metadata"]["ownerReferences"] = [{"kind": "ReplicaSet", "name": "web-abc"}]
        old_pods.append(p)
    server = Server(snapshot_fn=lambda: _snapshot(nodes=nodes, pods=old_pods, rs=[rs]))
    scaled = make_deployment("web", replicas=5, cpu="1", memory="1Gi")
    code, body = server.handle_scale_apps({"deployments": [scaled]})
    assert code == 200
    placed = sum(len(ns["pods"]) for ns in body["nodeStatus"])
    assert placed == 5  # old 2 removed, 5 new placed


def test_http_round_trip():
    nodes = [make_node("n1")]
    server = Server(snapshot_fn=lambda: _snapshot(nodes=nodes))
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["message"] == "ok"

        deploy = make_deployment("api", replicas=2, cpu="1", memory="1Gi")
        conn.request("POST", "/api/deploy-apps", body=json.dumps({"deployments": [deploy]}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        body = json.loads(resp.read())
        assert sum(len(ns["pods"]) for ns in body["nodeStatus"]) == 2

        # invalid UTF-8 body → in-band structured 400
        conn.request("POST", "/api/deploy-apps", body=b"\x80abc",
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 400
        body = json.loads(resp.read())
        assert "fail to unmarshal" in body["error"] and body["code"] == 400
    finally:
        httpd.shutdown()


def test_metrics_scrape_smoke():
    """GET /metrics: Prometheus text with the scheduler-parity counters the
    deploy request just moved."""
    nodes = [make_node("n1"), make_node("n2")]
    server = Server(snapshot_fn=lambda: _snapshot(nodes=nodes))
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        deploy = make_deployment("scrape", replicas=2, cpu="1", memory="1Gi")
        conn.request("POST", "/api/deploy-apps",
                     body=json.dumps({"deployments": [deploy]}),
                     headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 200

        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        text = resp.read().decode()
        assert "# TYPE simon_scheduling_attempts_total counter" in text
        assert 'simon_scheduling_attempts_total{result="scheduled"}' in text
        assert "# TYPE simon_e2e_scheduling_duration_seconds histogram" in text
        assert "simon_commits_total" in text

        # /debug/vars carries the flat view next to the recent traces
        conn.request("GET", "/debug/vars")
        body = json.loads(conn.getresponse().read())
        assert "metrics" in body
        assert any(k.startswith("simon_scheduling_attempts_total")
                   for k in body["metrics"])
    finally:
        httpd.shutdown()


def test_handler_exception_is_structured_counted_and_releases_lock():
    """A raising snapshot_fn yields a structured 500 (never a bare string),
    moves simon_http_errors_total, and leaves the endpoint lock released."""
    from open_simulator_tpu.obs import REGISTRY

    def boom():
        raise RuntimeError("apiserver exploded")

    server = Server(snapshot_fn=boom)

    def err_count():
        return sum(v for k, v in REGISTRY.values().items()
                   if k.startswith("simon_http_errors_total")
                   and 'endpoint="deploy-apps"' in k and '"500"' in k)

    before = err_count()
    code, body = server.handle_deploy_apps({})
    assert code == 500
    assert body["code"] == 500 and "apiserver exploded" in body["error"]
    assert err_count() == before + 1
    assert not server.deploy_lock.locked()


def test_debug_fault_plan_endpoint():
    """POST /debug/fault-plan installs a deterministic plan; the next deploy
    fails with a structured 500 naming the injected site; empty POST clears."""
    from open_simulator_tpu.resilience import active_plan, clear_plan

    nodes = [make_node("n1")]
    # the endpoint is a process-global write: strictly opt-in
    server = Server(snapshot_fn=lambda: _snapshot(nodes=nodes),
                    debug_faults=True)
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        plan = {"faults": [{"site": "encode", "attempt": 1, "error": "runtime"}]}
        conn.request("POST", "/debug/fault-plan", body=json.dumps(plan),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["faults"] == plan["faults"]

        deploy = make_deployment("web", replicas=2, cpu="1", memory="1Gi")
        conn.request("POST", "/api/deploy-apps",
                     body=json.dumps({"deployments": [deploy]}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 500
        assert "injected fault at encode" in json.loads(resp.read())["error"]

        # GET shows the fired trace; empty POST clears the plan
        conn.request("GET", "/debug/fault-plan")
        trace = json.loads(conn.getresponse().read())["trace"]
        assert ["encode", 1, "runtime"] in trace
        conn.request("POST", "/debug/fault-plan", body=b"{}",
                     headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        assert active_plan() is None

        conn.request("POST", "/api/deploy-apps",
                     body=json.dumps({"deployments": [deploy]}),
                     headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
    finally:
        clear_plan()
        httpd.shutdown()


def test_debug_fault_plan_endpoint_disabled_by_default():
    """Without the explicit opt-in, the write endpoint refuses with 403 —
    a reachable port must never be a one-request DoS."""
    server = Server(snapshot_fn=lambda: _snapshot(nodes=[make_node("n1")]))
    assert server.debug_faults is False
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/debug/fault-plan",
                     body=json.dumps({"faults": [{"site": "encode"}]}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 403 and "disabled" in body["error"]
        conn.request("GET", "/debug/fault-plan")
        assert conn.getresponse().status == 403
    finally:
        httpd.shutdown()
    from open_simulator_tpu.resilience import active_plan

    assert active_plan() is None


def test_graceful_drain_finishes_inflight_and_rejects_new():
    """Server.drain (the SIGTERM path): a slow in-flight request completes
    200 while requests arriving after drain started get structured 503s."""
    import time

    nodes = [make_node("n1")]
    release = threading.Event()
    entered = threading.Event()

    def slow_snapshot():
        entered.set()
        assert release.wait(timeout=30)
        return _snapshot(nodes=nodes)

    server = Server(snapshot_fn=slow_snapshot)
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    results = {}

    def inflight():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        deploy = make_deployment("web", replicas=1, cpu="1", memory="1Gi")
        conn.request("POST", "/api/deploy-apps",
                     body=json.dumps({"deployments": [deploy]}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        results["inflight"] = (resp.status, json.loads(resp.read()))

    t = threading.Thread(target=inflight)
    t.start()
    assert entered.wait(timeout=10)  # the slow request is now in flight

    drained = {}
    dt = threading.Thread(target=lambda: drained.update(
        stranded=server.drain(deadline=20.0)))
    dt.start()
    # draining flips synchronously; new requests are refused with 503
    deadline = time.monotonic() + 5
    while not server.draining and time.monotonic() < deadline:
        time.sleep(0.01)
    assert server.draining
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", "/healthz")
    resp = conn.getresponse()
    body = json.loads(resp.read())
    assert resp.status == 503 and body["code"] == 503
    assert "draining" in body["error"]

    release.set()  # let the in-flight request finish
    t.join(timeout=30)
    dt.join(timeout=30)
    assert results["inflight"][0] == 200
    assert drained["stranded"] == 0


def test_drain_deadline_bounds_stuck_requests():
    """A request that never finishes cannot hold the drain hostage: the
    bounded deadline expires and reports the stranded request."""
    nodes = [make_node("n1")]
    stuck = threading.Event()

    def stuck_snapshot():
        stuck.wait(timeout=60)
        return _snapshot(nodes=nodes)

    server = Server(snapshot_fn=stuck_snapshot)
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def hang():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        deploy = make_deployment("web", replicas=1, cpu="1", memory="1Gi")
        try:
            conn.request("POST", "/api/deploy-apps",
                         body=json.dumps({"deployments": [deploy]}),
                         headers={"Content-Type": "application/json"})
            conn.getresponse()
        except OSError:
            pass  # the drain may sever the connection

    t = threading.Thread(target=hang, daemon=True)
    t.start()
    import time
    deadline = time.monotonic() + 5
    while not server._inflight and time.monotonic() < deadline:
        time.sleep(0.01)
    stranded = server.drain(deadline=0.3)
    assert stranded == 1
    stuck.set()


@pytest.mark.skipif(sys.platform != "linux", reason="reads /proc/self/status")
def test_deploy_apps_rss_bounded_over_many_requests():
    """The reference's memory-leak postmortem (docs/design/内存泄漏.md: 1.23GiB
    RSS after 100 simulate POSTs, fixed by unblocking a leaked goroutine per
    request) is a regression class this design must not reintroduce: repeated
    what-if requests must not accumulate simulator state. After a warmup
    (compile + allocator high-water), 20 further requests may grow RSS only
    marginally."""

    def rss_kb():
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
        pytest.skip("no VmRSS line in /proc/self/status")

    nodes = [make_node(f"m{i}") for i in range(8)]
    server = Server(snapshot_fn=lambda: _snapshot(nodes=nodes))
    req = {"deployments": [make_deployment("soak", replicas=6, cpu="1", memory="1Gi")]}
    for _ in range(5):  # warmup: compiles + allocator high-water mark
        code, _ = server.handle_deploy_apps(req)
        assert code == 200
    base = rss_kb()
    for _ in range(20):
        code, body = server.handle_deploy_apps(req)
        assert code == 200
        assert sum(len(ns["pods"]) for ns in body["nodeStatus"]) == 6
    grown_mb = (rss_kb() - base) / 1024
    assert grown_mb < 100, f"RSS grew {grown_mb:.0f}MB over 20 requests"
