"""Integration tests for the batched scheduler, modeled on the reference's single big
oracle test (pkg/simulator/core_test.go:32-362): build a cluster with taints, labels,
affinities; deploy apps with every workload kind; assert placements against
independently recomputed expectations, not golden files."""

import numpy as np
import pytest

from fixtures import (
    make_daemonset,
    make_deployment,
    make_job,
    make_node,
    make_pod,
    make_replicaset,
    make_statefulset,
    master_taint,
    master_toleration,
)
from open_simulator_tpu import AppResource, ResourceTypes, simulate
from open_simulator_tpu.core import constants as C
from open_simulator_tpu.utils.objutil import annotations_of, labels_of


def pods_per_node(result):
    return {ns.node["metadata"]["name"]: ns.pods for ns in result.node_status}


class TestBasicPlacement:
    def test_all_fit(self):
        cluster = ResourceTypes(nodes=[make_node(f"w{i}", cpu="8", memory="16Gi") for i in range(4)])
        app = AppResource("a", ResourceTypes(
            deployments=[make_deployment("web", replicas=8, cpu="1", memory="1Gi")]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        counts = [len(p) for p in pods_per_node(res).values()]
        assert sum(counts) == 8
        # LeastAllocated symmetry → even spread
        assert max(counts) - min(counts) <= 1

    def test_capacity_exhaustion_reports_reason(self):
        cluster = ResourceTypes(nodes=[make_node("w0", cpu="2", memory="4Gi")])
        app = AppResource("a", ResourceTypes(
            deployments=[make_deployment("big", replicas=3, cpu="1500m", memory="1Gi")]))
        res = simulate(cluster, [app])
        assert len(res.unscheduled_pods) == 2  # only one 1.5-cpu pod fits in 2 cores
        assert "Insufficient cpu" in res.unscheduled_pods[0].reason
        assert "0/1 nodes are available" in res.unscheduled_pods[0].reason

    def test_pods_count_limit(self):
        cluster = ResourceTypes(nodes=[make_node("w0", cpu="64", memory="64Gi", pods="3")])
        app = AppResource("a", ResourceTypes(
            deployments=[make_deployment("tiny", replicas=5, cpu="10m", memory="16Mi")]))
        res = simulate(cluster, [app])
        assert len(res.unscheduled_pods) == 2
        assert "Too many pods" in res.unscheduled_pods[0].reason

    def test_bound_pods_consume_capacity_without_filtering(self):
        # a pre-bound cluster pod takes 7 of 8 cores; app pod then only fits elsewhere
        bound = make_pod("hog", cpu="7", memory="1Gi", node_name="w0")
        cluster = ResourceTypes(
            nodes=[make_node("w0", cpu="8", memory="16Gi"), make_node("w1", cpu="8", memory="16Gi")],
            pods=[bound],
        )
        app = AppResource("a", ResourceTypes(
            deployments=[make_deployment("d", replicas=1, cpu="4", memory="1Gi")]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        per = pods_per_node(res)
        assert any(p["metadata"]["name"] == "hog" for p in per["w0"])
        deploy_pod_nodes = [n for n, ps in per.items() for p in ps if p["metadata"]["name"] != "hog"]
        assert deploy_pod_nodes == ["w1"]


class TestTaintsAndSelectors:
    def test_taint_blocks_untolerated(self):
        cluster = ResourceTypes(nodes=[
            make_node("m0", taints=[master_taint()]),
            make_node("w0"),
        ])
        app = AppResource("a", ResourceTypes(
            deployments=[make_deployment("d", replicas=2, cpu="1", memory="1Gi")]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        per = pods_per_node(res)
        assert len(per["m0"]) == 0 and len(per["w0"]) == 2

    def test_toleration_allows_master(self):
        cluster = ResourceTypes(nodes=[make_node("m0", taints=[master_taint()])])
        app = AppResource("a", ResourceTypes(pods=[
            make_pod("p", cpu="1", memory="1Gi", tolerations=[master_toleration()])]))
        res = simulate(cluster, [app])
        assert res.all_scheduled

    def test_untolerated_taint_reason_names_taint(self):
        cluster = ResourceTypes(nodes=[make_node("m0", taints=[master_taint()])])
        app = AppResource("a", ResourceTypes(pods=[make_pod("p", cpu="1", memory="1Gi")]))
        res = simulate(cluster, [app])
        assert len(res.unscheduled_pods) == 1
        assert "node-role.kubernetes.io/master" in res.unscheduled_pods[0].reason

    def test_node_selector(self):
        cluster = ResourceTypes(nodes=[
            make_node("ssd0", labels={"disk": "ssd"}),
            make_node("hdd0", labels={"disk": "hdd"}),
        ])
        app = AppResource("a", ResourceTypes(pods=[
            make_pod("p", cpu="1", memory="1Gi", node_selector={"disk": "ssd"})]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        assert len(pods_per_node(res)["ssd0"]) == 1

    def test_required_node_affinity_gt(self):
        cluster = ResourceTypes(nodes=[
            make_node("n1", labels={"gen": "3"}),
            make_node("n2", labels={"gen": "7"}),
        ])
        aff = {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [{"matchExpressions": [
                {"key": "gen", "operator": "Gt", "values": ["5"]}]}]}}}
        app = AppResource("a", ResourceTypes(pods=[make_pod("p", affinity=aff)]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        assert len(pods_per_node(res)["n2"]) == 1

    def test_preferred_node_affinity_steers(self):
        cluster = ResourceTypes(nodes=[
            make_node("plain"),
            make_node("pref", labels={"tier": "gold"}),
        ])
        aff = {"nodeAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": 100, "preference": {"matchExpressions": [
                {"key": "tier", "operator": "In", "values": ["gold"]}]}}]}}
        app = AppResource("a", ResourceTypes(pods=[make_pod("p", affinity=aff)]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        assert len(pods_per_node(res)["pref"]) == 1

    def test_unschedulable_node(self):
        cluster = ResourceTypes(nodes=[make_node("off", unschedulable=True), make_node("on")])
        app = AppResource("a", ResourceTypes(pods=[make_pod("p")]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        assert len(pods_per_node(res)["on"]) == 1


class TestInterPodAffinity:
    def _anti_sts(self, name, replicas, required=True):
        anti = {
            "labelSelector": {"matchExpressions": [
                {"key": "app", "operator": "In", "values": [name]}]},
            "topologyKey": "kubernetes.io/hostname",
        }
        affinity = {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [anti]} if required else
            {"preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 100, "podAffinityTerm": anti}]}}
        return make_statefulset(name, replicas=replicas, cpu="500m", memory="512Mi",
                                affinity=affinity)

    def test_required_anti_affinity_one_per_node(self):
        cluster = ResourceTypes(nodes=[make_node(f"w{i}") for i in range(3)])
        app = AppResource("a", ResourceTypes(stateful_sets=[self._anti_sts("sts", 4)]))
        res = simulate(cluster, [app])
        # 3 nodes → 3 pods placed, 1 unschedulable (hostname anti-affinity)
        assert len(res.unscheduled_pods) == 1
        counts = [len(p) for p in pods_per_node(res).values()]
        assert counts == [1, 1, 1]
        assert "anti-affinity" in res.unscheduled_pods[0].reason

    def test_preferred_anti_affinity_spreads_then_packs(self):
        cluster = ResourceTypes(nodes=[make_node(f"w{i}") for i in range(2)])
        app = AppResource("a", ResourceTypes(stateful_sets=[self._anti_sts("sts", 4, required=False)]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        counts = sorted(len(p) for p in pods_per_node(res).values())
        assert counts == [2, 2]

    def test_required_affinity_colocates(self):
        cluster = ResourceTypes(nodes=[make_node(f"w{i}") for i in range(3)])
        base = make_pod("base", labels={"app": "db"})
        follower_aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "db"}},
            "topologyKey": "kubernetes.io/hostname"}]}}
        followers = [make_pod(f"f{i}", labels={"app": "web"}, affinity=follower_aff) for i in range(2)]
        app = AppResource("a", ResourceTypes(pods=[base] + followers))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        per = pods_per_node(res)
        base_node = next(n for n, ps in per.items() if any(p["metadata"]["name"] == "base" for p in ps))
        assert len(per[base_node]) == 3  # followers joined base

    def test_affinity_bootstrap_first_pod(self):
        # pod requiring affinity to its own label with no match anywhere → allowed
        cluster = ResourceTypes(nodes=[make_node("w0")])
        aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "solo"}},
            "topologyKey": "kubernetes.io/hostname"}]}}
        app = AppResource("a", ResourceTypes(pods=[make_pod("p", labels={"app": "solo"}, affinity=aff)]))
        res = simulate(cluster, [app])
        assert res.all_scheduled

    def test_affinity_unsatisfiable_when_no_match(self):
        # required affinity to a label the pod itself doesn't carry → unschedulable
        cluster = ResourceTypes(nodes=[make_node("w0")])
        aff = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"app": "ghost"}},
            "topologyKey": "kubernetes.io/hostname"}]}}
        app = AppResource("a", ResourceTypes(pods=[make_pod("p", labels={"app": "solo"}, affinity=aff)]))
        res = simulate(cluster, [app])
        assert len(res.unscheduled_pods) == 1
        assert "affinity" in res.unscheduled_pods[0].reason

    def test_existing_pod_anti_affinity_blocks_newcomer(self):
        # placed pod's anti-affinity term must repel a later pod matching its selector
        cluster = ResourceTypes(nodes=[make_node("w0"), make_node("w1")])
        guard_aff = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [{
            "labelSelector": {"matchLabels": {"team": "red"}},
            "topologyKey": "kubernetes.io/hostname"}]}}
        guard = make_pod("guard", labels={"team": "blue"}, affinity=guard_aff)
        intruder = make_pod("intruder", labels={"team": "red"})
        app = AppResource("a", ResourceTypes(pods=[guard, intruder]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        per = pods_per_node(res)
        guard_node = next(n for n, ps in per.items() if any(p["metadata"]["name"] == "guard" for p in ps))
        assert not any(p["metadata"]["name"] == "intruder" for p in per[guard_node])


class TestTopologySpread:
    def test_do_not_schedule_enforced(self):
        nodes = [make_node(f"w{i}", labels={"zone": f"z{i % 2}"}) for i in range(4)]
        cluster = ResourceTypes(nodes=nodes)
        tmpl_labels = {"app": "spread"}
        dep = make_deployment("spread", replicas=4, cpu="100m", memory="128Mi", labels=tmpl_labels)
        dep["spec"]["template"]["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "zone", "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": tmpl_labels}}]
        res = simulate(cluster, [AppResource("a", ResourceTypes(deployments=[dep]))])
        assert res.all_scheduled
        zone_counts = {}
        for n, ps in pods_per_node(res).items():
            z = next(nd for nd in nodes if nd["metadata"]["name"] == n)["metadata"]["labels"]["zone"]
            zone_counts[z] = zone_counts.get(z, 0) + len(ps)
        assert abs(zone_counts.get("z0", 0) - zone_counts.get("z1", 0)) <= 1

    def test_missing_topology_key_blocks(self):
        nodes = [make_node("w0", labels={"zone": "z0"}), make_node("nolabel")]
        cluster = ResourceTypes(nodes=nodes)
        pod = make_pod("p", cpu="100m", memory="128Mi", labels={"app": "x"})
        pod["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "zone", "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}}}]
        res = simulate(cluster, [AppResource("a", ResourceTypes(pods=[pod]))])
        assert res.all_scheduled
        assert len(pods_per_node(res)["w0"]) == 1  # nolabel node filtered


class TestDaemonSetsAndWorkloads:
    def test_daemonset_covers_eligible_nodes(self):
        nodes = [make_node("w0"), make_node("w1"), make_node("m0", taints=[master_taint()])]
        cluster = ResourceTypes(nodes=nodes, daemon_sets=[make_daemonset("agent")])
        res = simulate(cluster, [])
        assert res.all_scheduled
        per = pods_per_node(res)
        assert len(per["w0"]) == 1 and len(per["w1"]) == 1 and len(per["m0"]) == 0

    def test_app_daemonset_schedules_on_each_node(self):
        nodes = [make_node(f"w{i}") for i in range(3)]
        cluster = ResourceTypes(nodes=nodes)
        app = AppResource("a", ResourceTypes(daemon_sets=[make_daemonset("agent")]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        assert all(len(p) == 1 for p in pods_per_node(res).values())

    def test_mixed_app_like_core_test(self):
        """The shape of core_test.go's TestSimulate: multi-workload app on a mixed
        cluster; oracle = per-workload expected pod counts recomputed independently."""
        nodes = [
            make_node("master-1", cpu="8", memory="16Gi",
                      labels={"node-role.kubernetes.io/master": ""}, taints=[master_taint()]),
            make_node("worker-1", cpu="16", memory="32Gi"),
            make_node("worker-2", cpu="16", memory="32Gi"),
        ]
        cluster = ResourceTypes(nodes=nodes)
        app_rt = ResourceTypes(
            deployments=[make_deployment("web", replicas=4, cpu="1", memory="1Gi")],
            stateful_sets=[make_statefulset("db", replicas=2, cpu="2", memory="4Gi")],
            daemon_sets=[make_daemonset("log")],
            jobs=[make_job("batch", completions=3)],
            replica_sets=[make_replicaset("rs", replicas=2)],
            pods=[make_pod("single", cpu="500m", memory="512Mi", tolerations=[master_toleration()])],
        )
        res = simulate(cluster, [AppResource("app", app_rt)])
        assert res.all_scheduled, [u.reason for u in res.unscheduled_pods]
        # oracle: recompute expected counts per workload kind from inputs
        expected = {"web": 4, "db": 2, "log": 2, "batch": 3, "rs": 2, None: 1}
        got = {}
        for ns in res.node_status:
            for p in ns.pods:
                wl = annotations_of(p).get(C.AnnoWorkloadName)
                key = wl if wl else None
                got[key] = got.get(key, 0) + 1
        # deployment pods are annotated with the synthetic RS name (prefix "web-")
        merged = {}
        for k, v in got.items():
            if k and k.startswith("web-"):
                merged["web"] = merged.get("web", 0) + v
            else:
                merged[k] = merged.get(k, 0) + v
        assert merged == expected
        # every pod carries the app label
        for ns in res.node_status:
            for p in ns.pods:
                assert labels_of(p)[C.LabelAppName] == "app"

    def test_apps_deploy_in_order_and_accumulate_failures(self):
        cluster = ResourceTypes(nodes=[make_node("w0", cpu="4", memory="8Gi")])
        app1 = AppResource("first", ResourceTypes(
            deployments=[make_deployment("a", replicas=3, cpu="1", memory="1Gi")]))
        app2 = AppResource("second", ResourceTypes(
            deployments=[make_deployment("b", replicas=3, cpu="1", memory="1Gi")]))
        res = simulate(cluster, [app1, app2])
        # 4 cores: app1 takes 3, app2 fits 1, 2 unschedulable
        assert len(res.unscheduled_pods) == 2
        names = {u.pod["metadata"]["annotations"][C.AnnoWorkloadName] for u in res.unscheduled_pods}
        assert all(n.startswith("b-") for n in names)


class TestScoring:
    def test_selector_spread_via_cluster_service(self):
        # cluster Service selecting the app's pods activates SelectorSpread
        svc = {"apiVersion": "v1", "kind": "Service",
               "metadata": {"name": "svc", "namespace": "default"},
               "spec": {"selector": {"app": "spread-me"}}}
        nodes = [make_node(f"w{i}") for i in range(3)]
        cluster = ResourceTypes(nodes=nodes, services=[svc])
        app = AppResource("a", ResourceTypes(
            deployments=[make_deployment("spread-me", replicas=6, cpu="100m", memory="128Mi",
                                         labels={"app": "spread-me"})]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        counts = sorted(len(p) for p in pods_per_node(res).values())
        assert counts == [2, 2, 2]

    def test_binpacking_prefers_tighter_node_for_simon(self):
        # Simon max-share steers toward the node where the pod consumes a larger share?
        # No: Simon scores by share of allocatable (static per alloc); the *smaller*
        # node yields a higher share → higher Simon score → bin-packing signal.
        cluster = ResourceTypes(nodes=[
            make_node("big", cpu="16", memory="32Gi"),
            make_node("small", cpu="4", memory="8Gi"),
        ])
        app = AppResource("a", ResourceTypes(pods=[make_pod("p", cpu="2", memory="2Gi")]))
        res = simulate(cluster, [app])
        assert res.all_scheduled
        # combined score: LeastAllocated prefers big, Simon prefers small; just assert
        # determinism and that exactly one node got the pod
        total = sum(len(p) for p in pods_per_node(res).values())
        assert total == 1


class TestReviewRegressions:
    def test_distinct_host_ports_do_not_conflict(self):
        # two pods with different hostPorts must co-locate on one node
        cluster = ResourceTypes(nodes=[make_node("w0")])
        app = AppResource("a", ResourceTypes(pods=[
            make_pod("a", cpu="100m", memory="128Mi", host_ports=[8080]),
            make_pod("b", cpu="100m", memory="128Mi", host_ports=[9090]),
        ]))
        res = simulate(cluster, [app])
        assert res.all_scheduled, [u.reason for u in res.unscheduled_pods]

    def test_same_host_port_conflicts(self):
        cluster = ResourceTypes(nodes=[make_node("w0")])
        app = AppResource("a", ResourceTypes(pods=[
            make_pod("a", cpu="100m", memory="128Mi", host_ports=[8080]),
            make_pod("b", cpu="100m", memory="128Mi", host_ports=[8080]),
        ]))
        res = simulate(cluster, [app])
        assert len(res.unscheduled_pods) == 1
        assert "free ports" in res.unscheduled_pods[0].reason

    def test_bound_pod_order_is_serial(self):
        # unbound pod listed BEFORE a bound hog must be scheduled before the hog's
        # capacity lands (reference schedules strictly in list order)
        unbound = make_pod("early", cpu="4", memory="1Gi")
        hog = make_pod("hog", cpu="7", memory="1Gi", node_name="w0")
        cluster = ResourceTypes(nodes=[make_node("w0", cpu="8", memory="16Gi")],
                                pods=[unbound, hog])
        res = simulate(cluster, [])
        assert res.all_scheduled  # early fits before hog commits; node ends overcommitted
        assert len(pods_per_node(res)["w0"]) == 2


# ------------------------------------------------- preemption/volume inertness ----


def test_uniform_priorities_no_warning(caplog):
    import logging

    from open_simulator_tpu.simulator.engine import Simulator

    nodes = [make_node("n0")]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(3)]
    for p in pods:
        p["spec"]["priority"] = 1000  # one class: preemption provably inert
    with caplog.at_level(logging.WARNING, logger="open_simulator_tpu"):
        Simulator(nodes).schedule_pods(pods)
    assert not [r for r in caplog.records if "preemption" in r.getMessage()]


def test_mixed_priorities_arm_preemption_without_side_effects():
    """Mixed priorities arm the DefaultPreemption pass (tests/test_preemption.py
    covers its semantics); with enough capacity it changes nothing."""
    from open_simulator_tpu.simulator.engine import Simulator

    nodes = [make_node("n0")]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(3)]
    pods[0]["spec"]["priority"] = 1000
    pods[1]["spec"]["priority"] = 0
    sim = Simulator(nodes)
    assert sim.schedule_pods(pods) == []
    assert sim._preempt_armed
    assert sim.preempted == []
    assert len(sim.pods_on_node[0]) == 3


def test_pvc_volumes_rewritten_to_hostpath():
    """MakeValidPod parity (pkg/utils/utils.go:378-463): every PVC volume
    becomes hostPath /tmp before scheduling, so the volume filter plugins
    (VolumeBinding/NodeVolumeLimits/VolumeZone/VolumeRestrictions) have no PVC
    to act on for ANY reachable input — they are inert by construction (see
    PARITY.md 'Volume filter plugins')."""
    from open_simulator_tpu.core.types import ResourceTypes
    from open_simulator_tpu.models.workloads import expand_workloads_excluding_daemonsets

    dep = {
        "kind": "Deployment", "apiVersion": "apps/v1",
        "metadata": {"name": "db", "namespace": "default"},
        "spec": {
            "replicas": 2,
            "selector": {"matchLabels": {"app": "db"}},
            "template": {
                "metadata": {"labels": {"app": "db"}},
                "spec": {
                    "containers": [{"name": "c", "image": "db:1", "resources": {
                        "requests": {"cpu": "100m", "memory": "128Mi"}}}],
                    "volumes": [
                        {"name": "data",
                         "persistentVolumeClaim": {"claimName": "db-data"}},
                        {"name": "cfg", "configMap": {"name": "db-cfg"}},
                    ],
                },
            },
        },
    }
    rt = ResourceTypes()
    rt.deployments = [dep]
    pods = expand_workloads_excluding_daemonsets(rt)
    assert len(pods) == 2
    for p in pods:
        vols = p["spec"]["volumes"]
        data = next(v for v in vols if v["name"] == "data")
        assert "persistentVolumeClaim" not in data
        assert data["hostPath"] == {"path": "/tmp"}
        cfg = next(v for v in vols if v["name"] == "cfg")
        assert "configMap" in cfg  # only PVC volumes are rewritten
    # and such pods schedule without any volume filtering
    from open_simulator_tpu.simulator.engine import Simulator

    failed = Simulator([make_node("n0")]).schedule_pods(pods)
    assert not failed


def test_mixed_priorities_across_batches_arm():
    """Cluster pods and app pods are scheduled in separate calls; a priority
    gap BETWEEN the sets must still arm the preemption pass (the seen-set
    persists on the Simulator)."""
    from open_simulator_tpu.simulator.engine import Simulator

    nodes = [make_node("n0")]
    low = [make_pod("low", cpu="100m", memory="128Mi")]
    high = [make_pod("high", cpu="100m", memory="128Mi")]
    high[0]["spec"]["priority"] = 1000
    sim = Simulator(nodes)
    sim.schedule_pods(low)
    assert not sim._preempt_armed
    sim.schedule_pods(high)
    assert sim._preempt_armed


def test_failure_reasons_use_segment_state():
    """A pod failing in an early segment must be diagnosed against the state
    it failed under, not the end-of-batch state: here the porty pods fail on
    ports while the node still has cpu room, and a later segment fills the
    cpu — the reason must say ports, not insufficient cpu."""
    from open_simulator_tpu.simulator.engine import Simulator

    nodes = [make_node("n0", cpu="4", memory="8Gi")]
    porty = [make_pod(f"porty{i}", cpu="100m", memory="128Mi",
                      labels={"app": "porty"}, host_ports=[8080])
             for i in range(10)]
    fillers = [make_pod(f"fill{i}", cpu="300m", memory="256Mi",
                        labels={"app": "fill"}) for i in range(20)]
    sim = Simulator(nodes)
    failed = sim.schedule_pods(porty + fillers)
    porty_failures = [f for f in failed if "porty" in f.pod["metadata"]["name"]]
    assert porty_failures
    for f in porty_failures:
        assert "free ports" in f.reason
        assert "Insufficient cpu" not in f.reason
