"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding paths run
without TPU hardware (the driver separately dry-runs `__graft_entry__.dryrun_multichip`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from open_simulator_tpu.utils.devices import force_cpu_platform, request_cpu_devices

request_cpu_devices(8)
force_cpu_platform()

# The 8 virtual devices would auto-enable the engine's mesh path for every
# test (Simulator._resolve_mesh); keep the default suite single-device and let
# the parallel/mesh tests opt in with use_mesh=True.
os.environ.setdefault("OPEN_SIMULATOR_MESH", "0")
