"""Test harness: force an 8-device virtual CPU mesh so multi-chip sharding paths run
without TPU hardware (the driver separately dry-runs `__graft_entry__.dryrun_multichip`)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from open_simulator_tpu.utils.devices import force_cpu_platform, request_cpu_devices

request_cpu_devices(8)
force_cpu_platform()
