"""Unit tests for the host (string-world) layer: quantities, YAML IO, matchers,
workload expansion. Reference behaviors cited per test."""

import json

import pytest

from fixtures import (
    make_cronjob,
    make_daemonset,
    make_deployment,
    make_job,
    make_node,
    make_pod,
    make_replicaset,
    make_statefulset,
    master_taint,
)
from open_simulator_tpu.core import constants as C
from open_simulator_tpu.models import workloads as W
from open_simulator_tpu.utils import objutil as O
from open_simulator_tpu.utils.quantity import format_quantity, parse_milli, parse_quantity
from open_simulator_tpu.utils.validate import ValidationError, validate_pod
from open_simulator_tpu.utils.yamlio import bucket_objects, decode_yaml_content


class TestQuantity:
    def test_plain_and_suffixes(self):
        assert parse_quantity("4") == 4
        assert parse_quantity("1500m") == 1.5
        assert parse_quantity("128Mi") == 128 * 1024**2
        assert parse_quantity("16Gi") == 16 * 1024**3
        assert parse_quantity("61255492Ki") == 61255492 * 1024
        assert parse_quantity("2k") == 2000
        assert parse_quantity("1e3") == 1000
        assert parse_quantity("0.5") == 0.5
        assert parse_quantity(2) == 2

    def test_milli(self):
        assert parse_milli("1500m") == 1500
        assert parse_milli("2") == 2000
        assert parse_milli("0.1") == 100
        assert parse_milli("100m") == 100

    def test_format(self):
        assert format_quantity(0) == "0"
        assert format_quantity(1.5) == "1500m"
        assert format_quantity(4) == "4"


class TestMatchers:
    def test_label_selector(self):
        sel = {"matchLabels": {"app": "x"}, "matchExpressions": [{"key": "tier", "operator": "In", "values": ["fe"]}]}
        assert O.match_label_selector(sel, {"app": "x", "tier": "fe"})
        assert not O.match_label_selector(sel, {"app": "x", "tier": "be"})
        assert not O.match_label_selector(None, {"app": "x"})
        assert O.match_label_selector({}, {"anything": "goes"})  # empty selector matches all

    def test_expression_operators(self):
        labels = {"a": "1", "b": "5"}
        assert O.match_expression(labels, {"key": "a", "operator": "Exists"})
        assert not O.match_expression(labels, {"key": "z", "operator": "Exists"})
        assert O.match_expression(labels, {"key": "z", "operator": "DoesNotExist"})
        assert O.match_expression(labels, {"key": "b", "operator": "Gt", "values": ["4"]})
        assert not O.match_expression(labels, {"key": "b", "operator": "Lt", "values": ["4"]})
        assert O.match_expression(labels, {"key": "a", "operator": "NotIn", "values": ["2"]})

    def test_node_affinity_and_selector(self):
        node = make_node("n1", labels={"disk": "ssd"})
        pod = make_pod("p", node_selector={"disk": "ssd"})
        assert O.pod_matches_node_affinity(pod, node)
        pod2 = make_pod("p2", node_selector={"disk": "hdd"})
        assert not O.pod_matches_node_affinity(pod2, node)
        aff = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd"]}]}
                    ]
                }
            }
        }
        assert O.pod_matches_node_affinity(make_pod("p3", affinity=aff), node)

    def test_match_fields(self):
        node = make_node("worker-1")
        term = {"matchFields": [{"key": "metadata.name", "operator": "In", "values": ["worker-1"]}]}
        assert O.match_node_selector_term(node, term)
        term2 = {"matchFields": [{"key": "metadata.name", "operator": "In", "values": ["worker-2"]}]}
        assert not O.match_node_selector_term(node, term2)

    def test_taints(self):
        node = make_node("m", taints=[master_taint()])
        pod = make_pod("p")
        assert O.find_untolerated_taint(node, pod, ("NoSchedule", "NoExecute")) is not None
        tol = {"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}
        pod_t = make_pod("p2", tolerations=[tol])
        assert O.find_untolerated_taint(node, pod_t, ("NoSchedule", "NoExecute")) is None
        # empty-key Exists toleration tolerates everything
        pod_all = make_pod("p3", tolerations=[{"operator": "Exists"}])
        assert O.find_untolerated_taint(node, pod_all, ("NoSchedule", "NoExecute")) is None

    def test_pod_requests_max_of_init(self):
        pod = make_pod("p", cpu="1", memory="1Gi")
        pod["spec"]["initContainers"] = [
            {"name": "init", "image": "busybox", "resources": {"requests": {"cpu": "3", "memory": "256Mi"}}}
        ]
        req = O.pod_resource_requests(pod)
        assert req["cpu"] == 3000  # init dominates cpu (milli)
        assert req["memory"] == 1024**3  # containers dominate memory

    def test_host_ports_hostnetwork(self):
        pod = make_pod("p")
        pod["spec"]["hostNetwork"] = True
        pod["spec"]["containers"][0]["ports"] = [{"containerPort": 8080}]
        assert O.pod_host_ports(pod) == [("TCP", "0.0.0.0", 8080)]


class TestYamlIO:
    def test_multidoc_and_bucket(self):
        content = """
apiVersion: v1
kind: Node
metadata: {name: n1}
---
apiVersion: apps/v1
kind: Deployment
metadata: {name: d1}
"""
        rt = bucket_objects(decode_yaml_content([content]))
        assert len(rt.nodes) == 1 and len(rt.deployments) == 1

    def test_unknown_kind(self):
        from open_simulator_tpu.utils.yamlio import UnknownKindError

        with pytest.raises(UnknownKindError):
            bucket_objects([{"kind": "Gizmo"}])


class TestWorkloadExpansion:
    def test_deployment(self):
        pods = W.pods_from_deployment(make_deployment("web", replicas=3))
        assert len(pods) == 3
        for p in pods:
            assert p["metadata"]["name"].startswith("web-")
            assert O.annotations_of(p)[C.AnnoWorkloadKind] == "ReplicaSet"  # via synthetic RS
            assert p["spec"]["schedulerName"] == C.DefaultSchedulerName
        assert len({p["metadata"]["name"] for p in pods}) == 3

    def test_statefulset_ordinals_and_storage(self):
        vct = [
            {
                "metadata": {"name": "data"},
                "spec": {
                    "storageClassName": "open-local-lvm",
                    "resources": {"requests": {"storage": "10Gi"}},
                },
            }
        ]
        pods = W.pods_from_statefulset(make_statefulset("db", replicas=2, volume_claim_templates=vct))
        assert [p["metadata"]["name"] for p in pods] == ["db-0", "db-1"]
        vols = json.loads(O.annotations_of(pods[0])[C.AnnoPodLocalStorage])
        assert vols["volumes"][0]["kind"] == "LVM"
        assert vols["volumes"][0]["size"] == str(10 * 1024**3)

    def test_job_and_cronjob(self):
        assert len(W.pods_from_job(make_job("pi", completions=4))) == 4
        assert len(W.pods_from_cronjob(make_cronjob("cron", completions=2))) == 2

    def test_replicaset_default_one(self):
        rs = make_replicaset("rs")
        del rs["spec"]["replicas"]
        assert len(W.pods_from_replicaset(rs)) == 1

    def test_daemonset_skips_tainted_and_pins(self):
        nodes = [
            make_node("w1"),
            make_node("w2"),
            make_node("m1", taints=[master_taint()]),
        ]
        pods = W.pods_from_daemonset(make_daemonset("agent"), nodes)
        assert len(pods) == 2  # master skipped: taint untolerated
        terms = pods[0]["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"]
        assert terms[0]["matchFields"][0]["values"] == ["w1"]

    def test_daemonset_merges_affinity_terms(self):
        ds = make_daemonset(
            "agent",
            affinity={
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {
                                "matchExpressions": [
                                    {"key": "node-role.kubernetes.io/master", "operator": "DoesNotExist"}
                                ]
                            }
                        ]
                    }
                }
            },
        )
        nodes = [make_node("w1"), make_node("m1", labels={"node-role.kubernetes.io/master": ""})]
        pods = W.pods_from_daemonset(ds, nodes)
        # master excluded by the preserved matchExpressions, not by taints
        assert len(pods) == 1
        term = pods[0]["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"][0]
        assert "matchExpressions" in term and "matchFields" in term

    def test_make_valid_pod_sanitizes(self):
        pod = make_pod("p")
        pod["spec"]["containers"][0]["env"] = [{"name": "A", "value": "b"}]
        pod["spec"]["containers"][0]["livenessProbe"] = {"exec": {"command": ["true"]}}
        pod["spec"]["volumes"] = [{"name": "v", "persistentVolumeClaim": {"claimName": "c"}}]
        out = W.make_valid_pod(pod)
        c = out["spec"]["containers"][0]
        assert "env" not in c and "livenessProbe" not in c
        assert out["spec"]["volumes"][0]["hostPath"]["path"] == "/tmp"
        assert out["spec"]["dnsPolicy"] == "ClusterFirst"

    def test_validation_rejects_bad_pod(self):
        with pytest.raises(ValidationError):
            validate_pod({"metadata": {"name": "UPPER_bad"}, "spec": {"containers": [{"name": "c", "image": "i"}]}})
        with pytest.raises(ValidationError):
            validate_pod({"metadata": {"name": "ok"}, "spec": {"containers": []}})

    def test_fake_nodes(self):
        from open_simulator_tpu.models.fakenode import new_fake_nodes

        nodes = new_fake_nodes(make_node("template"), 3)
        assert len(nodes) == 3
        for n in nodes:
            assert n["metadata"]["name"].startswith("simon-")
            # marker label value is "" like NewFakeNode (utils.go:903-915)
            assert C.LabelNewNode in O.labels_of(n)
            assert O.labels_of(n)[C.LabelHostname] == n["metadata"]["name"]
        assert len({n["metadata"]["name"] for n in nodes}) == 3

    def test_expand_app(self):
        from open_simulator_tpu.core.types import ResourceTypes

        rt = ResourceTypes(
            deployments=[make_deployment("d", replicas=2)],
            daemon_sets=[make_daemonset("ds")],
            jobs=[make_job("j", completions=1)],
        )
        nodes = [make_node("n1"), make_node("n2")]
        pods = W.generate_valid_pods_from_app("myapp", rt, nodes)
        assert len(pods) == 2 + 2 + 1
        assert all(O.labels_of(p)[C.LabelAppName] == "myapp" for p in pods)


# --------------------------------------------------------- validation depth ----


def test_validate_pod_labels_ports_tolerations():
    import pytest

    from open_simulator_tpu.utils.validate import ValidationError, validate_pod

    def base():
        return {
            "metadata": {"name": "p", "namespace": "default", "labels": {}},
            "spec": {"containers": [{"name": "c", "image": "i"}]},
        }

    p = base()
    p["metadata"]["labels"] = {"bad key!": "v"}
    with pytest.raises(ValidationError, match="label key"):
        validate_pod(p)

    p = base()
    p["spec"]["containers"][0]["ports"] = [{"containerPort": 99999}]
    with pytest.raises(ValidationError, match="containerPort"):
        validate_pod(p)

    p = base()
    p["spec"]["containers"][0]["ports"] = [
        {"containerPort": 80, "hostPort": 8080},
        {"containerPort": 81, "hostPort": 8080},
    ]
    with pytest.raises(ValidationError, match="duplicate hostPort"):
        validate_pod(p)

    p = base()
    p["spec"]["tolerations"] = [{"key": "k", "operator": "Exists", "value": "x"}]
    with pytest.raises(ValidationError, match="operator Exists"):
        validate_pod(p)

    p = base()
    p["spec"]["topologySpreadConstraints"] = [
        {"maxSkew": 0, "topologyKey": "zone", "whenUnsatisfiable": "DoNotSchedule"}]
    with pytest.raises(ValidationError, match="maxSkew"):
        validate_pod(p)

    p = base()
    p["spec"]["affinity"] = {"nodeAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": {"nodeSelectorTerms": [
            {"matchExpressions": [{"key": "k", "operator": "In", "values": []}]}]}}}
    with pytest.raises(ValidationError, match="requires values"):
        validate_pod(p)

    p = base()
    p["spec"]["volumes"] = [{"name": "v", "hostPath": {"path": "/tmp"}},
                            {"name": "v", "hostPath": {"path": "/tmp"}}]
    with pytest.raises(ValidationError, match="duplicate name"):
        validate_pod(p)

    validate_pod(base())  # a clean pod still validates


def test_validate_node_taints_and_labels():
    import pytest

    from open_simulator_tpu.utils.validate import ValidationError, validate_node

    node = {"metadata": {"name": "n", "labels": {"ok": "yes"}},
            "spec": {"taints": [{"key": "k", "effect": "BadEffect"}]},
            "status": {"allocatable": {"cpu": "1"}}}
    with pytest.raises(ValidationError, match="invalid effect"):
        validate_node(node)
    node["spec"]["taints"][0]["effect"] = "NoSchedule"
    validate_node(node)
