"""Functional-option fixture builders, mirroring the reference's pkg/test builders
(/root/reference/pkg/test/{pod,node,deployment,...}.go) so tests read the same way."""

from __future__ import annotations

import copy
from typing import List, Optional


def make_node(
    name: str,
    cpu: str = "8",
    memory: str = "16Gi",
    pods: str = "110",
    labels: Optional[dict] = None,
    taints: Optional[List[dict]] = None,
    annotations: Optional[dict] = None,
    extra_resources: Optional[dict] = None,
    unschedulable: bool = False,
) -> dict:
    alloc = {"cpu": cpu, "memory": memory, "pods": pods, "ephemeral-storage": "100Gi"}
    if extra_resources:
        alloc.update(extra_resources)
    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {"kubernetes.io/hostname": name, **(labels or {})},
            "annotations": annotations or {},
        },
        "spec": {},
        "status": {"allocatable": copy.deepcopy(alloc), "capacity": copy.deepcopy(alloc)},
    }
    if taints:
        node["spec"]["taints"] = taints
    if unschedulable:
        node["spec"]["unschedulable"] = True
    return node


def make_pod(
    name: str,
    namespace: str = "default",
    cpu: str = "1",
    memory: str = "1Gi",
    labels: Optional[dict] = None,
    node_name: Optional[str] = None,
    node_selector: Optional[dict] = None,
    tolerations: Optional[List[dict]] = None,
    affinity: Optional[dict] = None,
    host_ports: Optional[List[int]] = None,
    annotations: Optional[dict] = None,
    no_requests: bool = False,
) -> dict:
    container = {"name": "main", "image": "busybox"}
    if not no_requests:
        container["resources"] = {"requests": {"cpu": cpu, "memory": memory}}
    if host_ports:
        container["ports"] = [{"containerPort": p, "hostPort": p} for p in host_ports]
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": labels or {},
            "annotations": annotations or {},
        },
        "spec": {"containers": [container]},
    }
    if node_name:
        pod["spec"]["nodeName"] = node_name
    if node_selector:
        pod["spec"]["nodeSelector"] = node_selector
    if tolerations:
        pod["spec"]["tolerations"] = tolerations
    if affinity:
        pod["spec"]["affinity"] = affinity
    return pod


def _template(labels: dict, cpu: str, memory: str, **spec_extra) -> dict:
    return {
        "metadata": {"labels": labels},
        "spec": {
            "containers": [
                {
                    "name": "main",
                    "image": "busybox",
                    "resources": {"requests": {"cpu": cpu, "memory": memory}},
                }
            ],
            **spec_extra,
        },
    }


def make_deployment(
    name: str,
    replicas: int = 1,
    namespace: str = "default",
    cpu: str = "1",
    memory: str = "1Gi",
    labels: Optional[dict] = None,
    **spec_extra,
) -> dict:
    labels = labels or {"app": name}
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": _template(labels, cpu, memory, **spec_extra),
        },
    }


def make_statefulset(
    name: str,
    replicas: int = 1,
    namespace: str = "default",
    cpu: str = "1",
    memory: str = "1Gi",
    labels: Optional[dict] = None,
    volume_claim_templates: Optional[List[dict]] = None,
    **spec_extra,
) -> dict:
    labels = labels or {"app": name}
    sts = {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "replicas": replicas,
            "serviceName": name,
            "selector": {"matchLabels": labels},
            "template": _template(labels, cpu, memory, **spec_extra),
        },
    }
    if volume_claim_templates:
        sts["spec"]["volumeClaimTemplates"] = volume_claim_templates
    return sts


def make_daemonset(
    name: str,
    namespace: str = "default",
    cpu: str = "500m",
    memory: str = "512Mi",
    labels: Optional[dict] = None,
    **spec_extra,
) -> dict:
    labels = labels or {"app": name}
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "selector": {"matchLabels": labels},
            "template": _template(labels, cpu, memory, **spec_extra),
        },
    }


def make_job(
    name: str, completions: int = 1, namespace: str = "default", cpu: str = "100m", memory: str = "100Mi"
) -> dict:
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "completions": completions,
            "template": {
                "metadata": {"labels": {"job-name": name}},
                "spec": {
                    "containers": [
                        {
                            "name": "main",
                            "image": "busybox",
                            "resources": {"requests": {"cpu": cpu, "memory": memory}},
                        }
                    ],
                    "restartPolicy": "Never",
                },
            },
        },
    }


def make_replicaset(
    name: str, replicas: int = 1, namespace: str = "default", cpu: str = "100m", memory: str = "128Mi",
    labels: Optional[dict] = None, **spec_extra,
) -> dict:
    labels = labels or {"app": name}
    return {
        "apiVersion": "apps/v1",
        "kind": "ReplicaSet",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": _template(labels, cpu, memory, **spec_extra),
        },
    }


def make_cronjob(
    name: str, namespace: str = "default", cpu: str = "100m", memory: str = "100Mi", completions: int = 1
) -> dict:
    return {
        "apiVersion": "batch/v1beta1",
        "kind": "CronJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "schedule": "*/5 * * * *",
            "jobTemplate": {
                "spec": {
                    "completions": completions,
                    "template": {
                        "metadata": {"labels": {"cron": name}},
                        "spec": {
                            "containers": [
                                {
                                    "name": "main",
                                    "image": "busybox",
                                    "resources": {"requests": {"cpu": cpu, "memory": memory}},
                                }
                            ],
                            "restartPolicy": "Never",
                        },
                    },
                }
            },
        },
    }


def master_taint() -> dict:
    return {"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}


def master_toleration() -> dict:
    return {"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}
