"""Live-cluster client: paginated LIST (limit/continue) and exec-credential
auth, against an in-process fake apiserver — the hardening behind the
reference's 3,000+-node claim (changelogs/v0.1.3.md)."""

import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from open_simulator_tpu.simulator.live import (
    KubeClient,
    LiveClusterError,
    create_cluster_resource_from_client,
)


def fake_apiserver(n_nodes=7, page=3, require_token=None):
    """Serves /api/v1/nodes with limit/continue pagination; other LISTs empty.
    Returns (httpd, port, seen_requests)."""
    nodes = [{"metadata": {"name": f"n{i}"},
              "status": {"allocatable": {"cpu": "1", "memory": "1Gi", "pods": "10"}}}
             for i in range(n_nodes)]
    seen = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            seen.append((u.path, q, self.headers.get("Authorization")))
            if require_token and self.headers.get("Authorization") != f"Bearer {require_token}":
                self.send_response(401)
                self.end_headers()
                return
            if u.path == "/api/v1/nodes":
                limit = int(q.get("limit", 0)) or len(nodes)
                start = int(q.get("continue", 0))
                items = nodes[start:start + limit]
                nxt = start + limit
                body = {"kind": "NodeList", "apiVersion": "v1", "items": items,
                        "metadata": ({"continue": str(nxt)} if nxt < len(nodes) else {})}
            else:
                kind = "PodList" if "pods" in u.path else "List"
                body = {"kind": kind, "apiVersion": "v1", "items": [], "metadata": {}}
            data = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1], seen


def write_kubeconfig(tmp_path, port, user=None):
    cfg = {
        "current-context": "c",
        "contexts": [{"name": "c", "context": {"cluster": "cl", "user": "u"}}],
        "clusters": [{"name": "cl", "cluster": {"server": f"http://127.0.0.1:{port}"}}],
        "users": [{"name": "u", "user": user or {}}],
    }
    p = tmp_path / "kubeconfig"
    import yaml

    p.write_text(yaml.safe_dump(cfg))
    return str(p)


def test_paginated_list_fetches_all_pages(tmp_path):
    httpd, port, seen = fake_apiserver(n_nodes=7, page=3)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.PAGE_LIMIT = 3
        nodes = client.list("/api/v1/nodes")
        assert [n["metadata"]["name"] for n in nodes] == [f"n{i}" for i in range(7)]
        # TypeMeta restored on every item from every page
        assert all(n["kind"] == "Node" and n["apiVersion"] == "v1" for n in nodes)
        node_reqs = [(p, q) for p, q, _ in seen if p == "/api/v1/nodes"]
        assert len(node_reqs) == 3  # 3 + 3 + 1
        assert all(q.get("limit") == "3" for _, q in node_reqs)
        assert node_reqs[1][1].get("continue") == "3"
    finally:
        httpd.shutdown()


def test_full_snapshot_uses_pagination(tmp_path):
    httpd, port, seen = fake_apiserver(n_nodes=5)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.PAGE_LIMIT = 2
        rt = create_cluster_resource_from_client(client)
        assert len(rt.nodes) == 5
        pod_reqs = [q for p, q, _ in seen if p == "/api/v1/pods"]
        # pagination params present; no resourceVersion=0 (it disables limit)
        assert all("resourceVersion" not in q for q in pod_reqs)
        assert all(q.get("limit") == "2" for q in pod_reqs)
    finally:
        httpd.shutdown()


def test_exec_credential_token(tmp_path):
    httpd, port, seen = fake_apiserver(n_nodes=2, require_token="exec-tok-123")
    try:
        plugin = tmp_path / "cred.py"
        plugin.write_text(
            "import json, os\n"
            "assert 'KUBERNETES_EXEC_INFO' in os.environ\n"
            "print(json.dumps({'apiVersion': 'client.authentication.k8s.io/v1beta1',"
            "'kind': 'ExecCredential', 'status': {'token': 'exec-tok-123'}}))\n")
        user = {"exec": {
            "apiVersion": "client.authentication.k8s.io/v1beta1",
            "command": sys.executable,
            "args": [str(plugin)],
            "env": [{"name": "CRED_MODE", "value": "token"}],
        }}
        client = KubeClient(write_kubeconfig(tmp_path, port, user=user))
        nodes = client.list("/api/v1/nodes")
        assert len(nodes) == 2
        assert all(auth == "Bearer exec-tok-123" for _, _, auth in seen)
    finally:
        httpd.shutdown()


def test_exec_credential_failure_is_loud(tmp_path):
    user = {"exec": {"command": sys.executable,
                     "args": ["-c", "import sys; sys.exit(3)"]}}
    with pytest.raises(LiveClusterError) as e:
        KubeClient(write_kubeconfig(tmp_path, 1, user=user))
    assert "exec credential" in str(e.value)
