"""Live-cluster client: paginated LIST (limit/continue), exec-credential
auth, and the simonfault failure policies (retry/backoff with Retry-After,
401-never-retry, 410-Gone relist, circuit breaker, deadline slicing) against
an in-process fake apiserver — the hardening behind the reference's
3,000+-node claim (changelogs/v0.1.3.md)."""

import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from open_simulator_tpu.obs import REGISTRY
from open_simulator_tpu.resilience.policy import (
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
)
from open_simulator_tpu.simulator.live import (
    AuthError,
    KubeClient,
    LiveClusterError,
    TransientError,
    create_cluster_resource_from_client,
)

FAST_RETRY = RetryPolicy(max_attempts=4, base=0.001, mult=2.0, cap=0.01,
                         jitter=0.0, max_elapsed=10.0, seed=0)


def fake_apiserver(n_nodes=7, page=3, require_token=None):
    """Serves /api/v1/nodes with limit/continue pagination; other LISTs empty.
    Returns (httpd, port, seen_requests)."""
    nodes = [{"metadata": {"name": f"n{i}"},
              "status": {"allocatable": {"cpu": "1", "memory": "1Gi", "pods": "10"}}}
             for i in range(n_nodes)]
    seen = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            seen.append((u.path, q, self.headers.get("Authorization")))
            if require_token and self.headers.get("Authorization") != f"Bearer {require_token}":
                self.send_response(401)
                self.end_headers()
                return
            if u.path == "/api/v1/nodes":
                limit = int(q.get("limit", 0)) or len(nodes)
                start = int(q.get("continue", 0))
                items = nodes[start:start + limit]
                nxt = start + limit
                body = {"kind": "NodeList", "apiVersion": "v1", "items": items,
                        "metadata": ({"continue": str(nxt)} if nxt < len(nodes) else {})}
            else:
                kind = "PodList" if "pods" in u.path else "List"
                body = {"kind": kind, "apiVersion": "v1", "items": [], "metadata": {}}
            data = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1], seen


def write_kubeconfig(tmp_path, port, user=None):
    cfg = {
        "current-context": "c",
        "contexts": [{"name": "c", "context": {"cluster": "cl", "user": "u"}}],
        "clusters": [{"name": "cl", "cluster": {"server": f"http://127.0.0.1:{port}"}}],
        "users": [{"name": "u", "user": user or {}}],
    }
    p = tmp_path / "kubeconfig"
    import yaml

    p.write_text(yaml.safe_dump(cfg))
    return str(p)


def test_paginated_list_fetches_all_pages(tmp_path):
    httpd, port, seen = fake_apiserver(n_nodes=7, page=3)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.PAGE_LIMIT = 3
        nodes = client.list("/api/v1/nodes")
        assert [n["metadata"]["name"] for n in nodes] == [f"n{i}" for i in range(7)]
        # TypeMeta restored on every item from every page
        assert all(n["kind"] == "Node" and n["apiVersion"] == "v1" for n in nodes)
        node_reqs = [(p, q) for p, q, _ in seen if p == "/api/v1/nodes"]
        assert len(node_reqs) == 3  # 3 + 3 + 1
        assert all(q.get("limit") == "3" for _, q in node_reqs)
        assert node_reqs[1][1].get("continue") == "3"
    finally:
        httpd.shutdown()


def test_full_snapshot_uses_pagination(tmp_path):
    httpd, port, seen = fake_apiserver(n_nodes=5)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.PAGE_LIMIT = 2
        rt = create_cluster_resource_from_client(client)
        assert len(rt.nodes) == 5
        pod_reqs = [q for p, q, _ in seen if p == "/api/v1/pods"]
        # pagination params present; no resourceVersion=0 (it disables limit)
        assert all("resourceVersion" not in q for q in pod_reqs)
        assert all(q.get("limit") == "2" for q in pod_reqs)
    finally:
        httpd.shutdown()


def test_exec_credential_token(tmp_path):
    httpd, port, seen = fake_apiserver(n_nodes=2, require_token="exec-tok-123")
    try:
        plugin = tmp_path / "cred.py"
        plugin.write_text(
            "import json, os\n"
            "assert 'KUBERNETES_EXEC_INFO' in os.environ\n"
            "print(json.dumps({'apiVersion': 'client.authentication.k8s.io/v1beta1',"
            "'kind': 'ExecCredential', 'status': {'token': 'exec-tok-123'}}))\n")
        user = {"exec": {
            "apiVersion": "client.authentication.k8s.io/v1beta1",
            "command": sys.executable,
            "args": [str(plugin)],
            "env": [{"name": "CRED_MODE", "value": "token"}],
        }}
        client = KubeClient(write_kubeconfig(tmp_path, port, user=user))
        nodes = client.list("/api/v1/nodes")
        assert len(nodes) == 2
        assert all(auth == "Bearer exec-tok-123" for _, _, auth in seen)
    finally:
        httpd.shutdown()


def test_exec_credential_failure_is_loud(tmp_path):
    user = {"exec": {"command": sys.executable,
                     "args": ["-c", "import sys; sys.exit(3)"]}}
    with pytest.raises(AuthError) as e:  # typed: retrying cannot help
        KubeClient(write_kubeconfig(tmp_path, 1, user=user))
    assert "exec credential" in str(e.value)
    assert isinstance(e.value, LiveClusterError)  # compat: old name still catches


# ------------------------------------------------- failure-policy behavior ----


def scripted_apiserver(n_nodes=5, script=None):
    """Like fake_apiserver, but each request first consults `script`: a
    mutable list of {"status": int, "headers": {...}, "require_continue":
    bool} entries. The first matching entry is popped and served as the
    response; with no match the normal paginated answer goes out. Returns
    (httpd, port, seen)."""
    nodes = [{"metadata": {"name": f"n{i}"},
              "status": {"allocatable": {"cpu": "1", "memory": "1Gi", "pods": "10"}}}
             for i in range(n_nodes)]
    script = script if script is not None else []
    seen = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            seen.append((u.path, q))
            for i, entry in enumerate(script):
                if entry.get("require_continue") and "continue" not in q:
                    continue
                script.pop(i)
                if entry.get("truncate"):
                    # promise a body and drop the connection mid-read:
                    # the client sees http.client.IncompleteRead
                    self.send_response(200)
                    self.send_header("Content-Length", "100")
                    self.end_headers()
                    self.wfile.write(b"x")
                    self.wfile.flush()
                    self.connection.close()
                    return
                self.send_response(entry["status"])
                for k, v in (entry.get("headers") or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            if u.path == "/api/v1/nodes":
                limit = int(q.get("limit", 0)) or len(nodes)
                start = int(q.get("continue", 0))
                items = nodes[start:start + limit]
                nxt = start + limit
                body = {"kind": "NodeList", "apiVersion": "v1", "items": items,
                        "metadata": ({"continue": str(nxt)} if nxt < len(nodes) else {})}
            else:
                body = {"kind": "List", "apiVersion": "v1", "items": [],
                        "metadata": {}}
            data = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1], seen


def _retry_count(site):
    return sum(v for k, v in REGISTRY.values().items()
               if k.startswith("simon_retries_total") and f'"{site}"' in k)


def test_transient_5xx_retried_then_succeeds(tmp_path):
    httpd, port, seen = scripted_apiserver(
        n_nodes=3, script=[{"status": 503}, {"status": 500}])
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = FAST_RETRY
        before = _retry_count("live_get")
        nodes = client.list("/api/v1/nodes")
        assert len(nodes) == 3
        assert len(seen) == 3  # 503, 500, then the successful page
        assert _retry_count("live_get") - before == 2
    finally:
        httpd.shutdown()


def test_connection_dropped_mid_body_is_transient_and_retried(tmp_path):
    # IncompleteRead is an http.client.HTTPException, NOT an OSError: it must
    # still classify TransientError (and so stay catchable as LiveClusterError)
    httpd, port, seen = scripted_apiserver(
        n_nodes=2, script=[{"truncate": True}])
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = FAST_RETRY
        nodes = client.list("/api/v1/nodes")
        assert len(nodes) == 2 and len(seen) == 2
    finally:
        httpd.shutdown()


def test_429_honors_retry_after_floor(tmp_path):
    httpd, port, seen = scripted_apiserver(
        n_nodes=1, script=[{"status": 429, "headers": {"Retry-After": "0.3"}}])
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = FAST_RETRY  # backoff alone would sleep ~1ms
        t0 = time.perf_counter()
        nodes = client.list("/api/v1/nodes")
        elapsed = time.perf_counter() - t0
        assert len(nodes) == 1 and len(seen) == 2
        assert elapsed >= 0.3, f"Retry-After not honored ({elapsed:.3f}s)"
    finally:
        httpd.shutdown()


def test_auth_errors_never_retried(tmp_path):
    for status in (401, 403):
        httpd, port, seen = scripted_apiserver(
            n_nodes=1, script=[{"status": status}, {"status": status}])
        try:
            client = KubeClient(write_kubeconfig(tmp_path, port))
            client.retry = FAST_RETRY
            with pytest.raises(AuthError):
                client.list("/api/v1/nodes")
            assert len(seen) == 1, f"{status} must fail on the FIRST attempt"
        finally:
            httpd.shutdown()


def test_410_gone_restarts_pagination_from_scratch(tmp_path):
    # the continue token "expires" once mid-pagination: the partial result is
    # discarded and the LIST restarts — no duplicates, no gaps (client-go
    # reflector relist semantics)
    httpd, port, seen = scripted_apiserver(
        n_nodes=5, script=[{"status": 410, "require_continue": True}])
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = FAST_RETRY
        client.PAGE_LIMIT = 2
        nodes = client.list("/api/v1/nodes")
        assert [n["metadata"]["name"] for n in nodes] == [f"n{i}" for i in range(5)]
        # first pass: page + failed continue; restart: 3 clean pages
        assert len(seen) == 5
    finally:
        httpd.shutdown()


def test_410_gone_relists_are_bounded(tmp_path):
    httpd, port, _seen = scripted_apiserver(
        n_nodes=5,
        script=[{"status": 410, "require_continue": True}] * 10)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = FAST_RETRY
        client.PAGE_LIMIT = 2
        with pytest.raises(LiveClusterError):
            client.list("/api/v1/nodes")  # MAX_RELISTS exhausted: loud failure
    finally:
        httpd.shutdown()


def test_breaker_opens_after_consecutive_failures_and_fails_fast(tmp_path):
    httpd, port, seen = scripted_apiserver(
        n_nodes=1, script=[{"status": 500}] * 10)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = RetryPolicy(max_attempts=1, base=0.001)
        client.breaker = CircuitBreaker("live_test", failure_threshold=2,
                                        reset_after=60.0)
        for _ in range(2):
            with pytest.raises(TransientError):
                client.get("/api/v1/nodes")
        n_before = len(seen)
        with pytest.raises(BreakerOpen):
            client.get("/api/v1/nodes")
        assert len(seen) == n_before, "open breaker must not touch the server"
    finally:
        httpd.shutdown()


def test_deadline_bounds_live_gets(tmp_path):
    httpd, port, seen = scripted_apiserver(n_nodes=1)
    try:
        client = KubeClient(write_kubeconfig(tmp_path, port))
        client.retry = FAST_RETRY
        with Deadline(30.0):
            assert len(client.list("/api/v1/nodes")) == 1  # budget left: works
        time.sleep(0.002)
        with Deadline(0.001):
            time.sleep(0.005)  # budget gone before the call
            n_before = len(seen)
            with pytest.raises(DeadlineExceeded):
                client.get("/api/v1/nodes")
            assert len(seen) == n_before
    finally:
        httpd.shutdown()
