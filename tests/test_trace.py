"""Phase tracing + progress reporting (the reference's utiltrace spans with
LogIfLong thresholds, core.go:67-73, and the pterm progress bar,
simulator.go:311-321)."""

import io
import logging

from open_simulator_tpu.utils.trace import Progress, Span, recent_spans

from fixtures import make_node, make_pod


def test_span_logs_only_over_threshold(caplog):
    with caplog.at_level(logging.WARNING, logger="open_simulator_tpu.trace"):
        with Span("fast phase", log_if_longer=10.0) as sp:
            sp.step("a")
        assert not caplog.records
        with Span("slow phase", log_if_longer=0.0) as sp:
            sp.step("b")
        assert any("slow phase" in r.getMessage() for r in caplog.records)
    spans = recent_spans()
    assert spans[0]["name"] == "slow phase" and spans[0]["logged"]
    assert spans[0]["steps"][0]["name"] == "b"
    assert spans[1]["name"] == "fast phase" and not spans[1]["logged"]


def test_span_nesting_attaches_children_to_parent():
    with Span("outer", log_if_longer=99.0) as outer:
        with Span("inner", log_if_longer=99.0) as inner:
            inner.step("work")
        with Span("inner2", log_if_longer=99.0):
            pass
    assert [c.name for c in outer.children] == ["inner", "inner2"]
    spans = recent_spans()
    # only the ROOT registers in the ring; children nest under it
    assert spans[0]["name"] == "outer"
    assert [c["name"] for c in spans[0]["children"]] == ["inner", "inner2"]
    assert spans[0]["children"][0]["steps"][0]["name"] == "work"
    assert all(s["name"] != "inner" for s in spans)


def test_span_exception_safety_records_partial_and_failed():
    import pytest

    with pytest.raises(RuntimeError):
        with Span("outer", log_if_longer=99.0):
            with pytest.raises(RuntimeError):
                with Span("dies", log_if_longer=99.0) as sp:
                    sp.step("before")
                    raise RuntimeError("boom")
            raise RuntimeError("outer dies too")
    spans = recent_spans()
    assert spans[0]["name"] == "outer" and spans[0]["failed"]
    child = spans[0]["children"][0]
    assert child["name"] == "dies" and child["failed"]
    assert child["steps"][0]["name"] == "before"  # partial steps survive
    # the active-span stack unwound: a fresh span is a root again
    with Span("clean", log_if_longer=99.0):
        pass
    assert recent_spans()[0]["name"] == "clean"
    assert not recent_spans()[0]["failed"]


def test_span_collection_for_trace_export():
    from open_simulator_tpu.utils.trace import start_collection, stop_collection

    start_collection()
    with Span("collected", log_if_longer=99.0):
        with Span("kid", log_if_longer=99.0):
            pass
    out = stop_collection()
    assert [s.name for s in out] == ["collected"]
    assert [c.name for c in out[0].children] == ["kid"]
    # collection is off again: nothing accumulates
    with Span("later", log_if_longer=99.0):
        pass
    assert stop_collection() == []


def test_simulate_emits_span():
    from open_simulator_tpu.core.types import AppResource, ResourceTypes
    from open_simulator_tpu.simulator.core import simulate

    cluster = ResourceTypes()
    cluster.nodes = [make_node("n0")]
    cluster.pods = [make_pod("p0", cpu="1", memory="1Gi")]
    simulate(cluster, [])
    names = [s["name"] for s in recent_spans()]
    assert "Simulate" in names
    sim_span = next(s for s in recent_spans() if s["name"] == "Simulate")
    step_names = [st["name"] for st in sim_span["steps"]]
    assert "expand cluster workloads" in step_names
    assert "sync cluster" in step_names


def test_progress_renders_and_closes():
    buf = io.StringIO()
    pr = Progress("Scheduling pods", 4, enabled=True, stream=buf)
    pr.advance(2)
    pr.advance(2)
    pr.close()
    out = buf.getvalue()
    assert "Scheduling pods 4/4 (100%)" in out
    assert out.endswith("\n")


def test_progress_disabled_is_silent():
    buf = io.StringIO()
    pr = Progress("x", 4, enabled=False, stream=buf)
    pr.advance(4)
    pr.close()
    assert buf.getvalue() == ""


def test_engine_progress_wiring():
    """disable_progress=False must actually render (the round-2 gap: a dead
    parameter)."""
    import contextlib
    import copy
    import io as _io
    import sys

    from open_simulator_tpu.simulator.engine import Simulator

    nodes = [make_node("n0")]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(12)]
    sim = Simulator(copy.deepcopy(nodes), disable_progress=False)
    buf = _io.StringIO()
    with contextlib.redirect_stderr(buf):
        sim.schedule_pods(copy.deepcopy(pods))
    assert "Scheduling pods 12/12" in buf.getvalue()


def test_server_debug_vars():
    import json
    import threading
    import urllib.request

    from open_simulator_tpu.server.http import Server

    srv = Server(snapshot_fn=lambda: None)  # endpoint needs no cluster client
    httpd = srv.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/debug/vars") as r:
            data = json.loads(r.read())
        assert "uptime_seconds" in data and "recent_traces" in data
        assert "max_rss_kb" in data
    finally:
        httpd.shutdown()


def test_server_debug_pprof_profile_samples_other_threads():
    """The sampler must see application work on OTHER threads — the bug this
    replaces: cProfile around a sleep only ever profiled the sleeping
    handler thread, so dumps were empty of application work."""
    import threading
    import urllib.request

    from open_simulator_tpu.server.http import Server

    stop = threading.Event()

    def busy_app_work():
        while not stop.is_set():
            sum(i * i for i in range(1000))

    worker = threading.Thread(target=busy_app_work, daemon=True)
    worker.start()
    srv = Server(snapshot_fn=lambda: None)
    httpd = srv.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/pprof/profile?seconds=0.3") as r:
            text = r.read().decode()
    finally:
        stop.set()
        httpd.shutdown()
    assert "stack samples:" in text
    assert "busy_app_work" in text  # the application thread was captured


def test_sample_stacks_excludes_caller_and_counts():
    from open_simulator_tpu.server.http import sample_stacks

    text = sample_stacks(0.05, interval=0.01)
    assert text.startswith("stack samples:")
    # the profiling thread itself never appears
    assert "sample_stacks" not in text.split("\n", 1)[1]
