"""Phase tracing + progress reporting (the reference's utiltrace spans with
LogIfLong thresholds, core.go:67-73, and the pterm progress bar,
simulator.go:311-321)."""

import io
import logging

from open_simulator_tpu.utils.trace import Progress, Span, recent_spans

from fixtures import make_node, make_pod


def test_span_logs_only_over_threshold(caplog):
    with caplog.at_level(logging.WARNING, logger="open_simulator_tpu.trace"):
        with Span("fast phase", log_if_longer=10.0) as sp:
            sp.step("a")
        assert not caplog.records
        with Span("slow phase", log_if_longer=0.0) as sp:
            sp.step("b")
        assert any("slow phase" in r.getMessage() for r in caplog.records)
    spans = recent_spans()
    assert spans[0]["name"] == "slow phase" and spans[0]["logged"]
    assert spans[0]["steps"][0]["name"] == "b"
    assert spans[1]["name"] == "fast phase" and not spans[1]["logged"]


def test_simulate_emits_span():
    from open_simulator_tpu.core.types import AppResource, ResourceTypes
    from open_simulator_tpu.simulator.core import simulate

    cluster = ResourceTypes()
    cluster.nodes = [make_node("n0")]
    cluster.pods = [make_pod("p0", cpu="1", memory="1Gi")]
    simulate(cluster, [])
    names = [s["name"] for s in recent_spans()]
    assert "Simulate" in names
    sim_span = next(s for s in recent_spans() if s["name"] == "Simulate")
    step_names = [st["name"] for st in sim_span["steps"]]
    assert "expand cluster workloads" in step_names
    assert "sync cluster" in step_names


def test_progress_renders_and_closes():
    buf = io.StringIO()
    pr = Progress("Scheduling pods", 4, enabled=True, stream=buf)
    pr.advance(2)
    pr.advance(2)
    pr.close()
    out = buf.getvalue()
    assert "Scheduling pods 4/4 (100%)" in out
    assert out.endswith("\n")


def test_progress_disabled_is_silent():
    buf = io.StringIO()
    pr = Progress("x", 4, enabled=False, stream=buf)
    pr.advance(4)
    pr.close()
    assert buf.getvalue() == ""


def test_engine_progress_wiring():
    """disable_progress=False must actually render (the round-2 gap: a dead
    parameter)."""
    import contextlib
    import copy
    import io as _io
    import sys

    from open_simulator_tpu.simulator.engine import Simulator

    nodes = [make_node("n0")]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(12)]
    sim = Simulator(copy.deepcopy(nodes), disable_progress=False)
    buf = _io.StringIO()
    with contextlib.redirect_stderr(buf):
        sim.schedule_pods(copy.deepcopy(pods))
    assert "Scheduling pods 12/12" in buf.getvalue()


def test_server_debug_vars():
    import json
    import threading
    import urllib.request

    from open_simulator_tpu.server.http import Server

    srv = Server.__new__(Server)  # endpoint needs no cluster client
    httpd = srv.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/debug/vars") as r:
            data = json.loads(r.read())
        assert "uptime_seconds" in data and "recent_traces" in data
        assert "max_rss_kb" in data
    finally:
        httpd.shutdown()


def test_server_debug_pprof_profile():
    import threading
    import urllib.request

    from open_simulator_tpu.server.http import Server

    srv = Server.__new__(Server)
    httpd = srv.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/pprof/profile?seconds=0.1") as r:
            text = r.read().decode()
        assert "cumulative" in text  # a pstats table came back
    finally:
        httpd.shutdown()
