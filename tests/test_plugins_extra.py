"""Out-of-tree plugin extension point (WithFrameworkOutOfTreeRegistry parity,
simulator.go:471-500): custom filter/score plugins fold into the static
tables and work identically through serial, wave, and simulate() paths."""

import copy

from open_simulator_tpu.core.types import AppResource, ResourceTypes
from open_simulator_tpu.plugins.registry import SimulatorPlugin
from open_simulator_tpu.simulator.core import simulate
from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod


class FpgaFilter(SimulatorPlugin):
    """Extended resource the kernel knows nothing about: pods requesting
    example.com/fpga only fit nodes advertising enough."""

    name = "example.com/fpga"

    def filter(self, pod, node):
        want = int((pod.get("metadata", {}).get("annotations") or {})
                   .get("example.com/fpga", 0))
        have = int(((node.get("status") or {}).get("allocatable") or {})
                   .get("example.com/fpga", 0))
        return want <= have


class PreferLabeled(SimulatorPlugin):
    name = "prefer-labeled"
    weight = 1000.0  # dominate the built-in scores

    def score(self, pod, node):
        lbls = (node.get("metadata") or {}).get("labels") or {}
        return 100.0 if lbls.get("tier") == "gold" else 0.0


def test_extra_filter_blocks_and_reports():
    nodes = [make_node("plain"),
             make_node("fpga", extra_resources={"example.com/fpga": "2"})]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi",
                     annotations={"example.com/fpga": "1"}) for i in range(3)]
    sim = Simulator(copy.deepcopy(nodes), extra_plugins=[FpgaFilter()])
    failed = sim.schedule_pods(copy.deepcopy(pods))
    assert not failed
    assert all(len(p) == 0 for p in [sim.pods_on_node[0]])  # plain got nothing
    assert len(sim.pods_on_node[1]) == 3

    # unsatisfiable request: the FitError names the out-of-tree plugin
    big = [make_pod("big", cpu="100m", memory="128Mi",
                    annotations={"example.com/fpga": "5"})]
    failed = sim.schedule_pods(copy.deepcopy(big))
    assert len(failed) == 1
    assert "out-of-tree plugin" in failed[0].reason


def test_extra_score_changes_placement():
    nodes = [make_node("silver"), make_node("gold", labels={"tier": "gold"})]
    pods = [make_pod("p", cpu="100m", memory="128Mi")]
    base = Simulator(copy.deepcopy(nodes))
    base.schedule_pods(copy.deepcopy(pods))
    assert len(base.pods_on_node[0]) == 1  # lowest-index tie-break by default

    boosted = Simulator(copy.deepcopy(nodes), extra_plugins=[PreferLabeled()])
    boosted.schedule_pods(copy.deepcopy(pods))
    assert len(boosted.pods_on_node[1]) == 1  # plugin score wins


def test_extra_plugins_wave_serial_equal():
    nodes = [make_node(f"n{i}", labels=({"tier": "gold"} if i % 3 == 0 else {}),
                       cpu="4", memory="8Gi") for i in range(6)]
    pods = [make_pod(f"w{i}", cpu="250m", memory="256Mi", labels={"app": "w"})
            for i in range(30)]
    results = []
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes), extra_plugins=[PreferLabeled()])
        sim.use_waves = waves
        failed = sim.schedule_pods(copy.deepcopy(pods))
        results.append(([len(p) for p in sim.pods_on_node], len(failed)))
    assert results[0] == results[1]


def test_simulate_facade_accepts_extra_plugins():
    cluster = ResourceTypes()
    cluster.nodes = [make_node("plain"),
                     make_node("fpga", extra_resources={"example.com/fpga": "4"})]
    app = ResourceTypes()
    app.pods = [make_pod("p0", cpu="100m", memory="128Mi",
                         annotations={"example.com/fpga": "1"})]
    res = simulate(cluster, [AppResource(name="a", resource=app)],
                   extra_plugins=[FpgaFilter()])
    assert not res.unscheduled_pods
    placed = {ns.node["metadata"]["name"]: len(ns.pods) for ns in res.node_status}
    assert placed == {"plain": 0, "fpga": 1}
