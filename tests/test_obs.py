"""simonmetrics: registry semantics, Prometheus rendering, Chrome export,
and the engine integration invariants the CI smoke also enforces."""

import json
import threading

import pytest

from open_simulator_tpu.obs.chrome import chrome_trace
from open_simulator_tpu.obs.metrics import (
    Registry,
    render_text_from_snapshot,
)
from open_simulator_tpu.utils.trace import Span

from pathlib import Path

GOLDEN = Path(__file__).parent / "golden" / "metrics.prom"


def _golden_registry() -> Registry:
    """A deterministic registry exercising every metric type, labels, label
    escaping, and histogram bucket arithmetic — the golden-file subject."""
    reg = Registry()
    c = reg.counter("demo_requests_total", "Requests served.", ("code", "verb"))
    c.labels(code="200", verb="GET").inc()
    c.labels(code="200", verb="GET").inc(2)
    c.labels(code="503", verb="POST").inc()
    g = reg.gauge("demo_queue_depth", "Items queued.")
    g.set(7)
    g.inc(1.5)
    h = reg.histogram("demo_latency_seconds", "Latencies.",
                      buckets=(0.1, 0.5, 1.0))
    for v in (0.05, 0.1, 0.3, 0.5, 0.9, 1.0, 4.0):
        h.observe(v)
    esc = reg.counter("demo_reasons_total", "Labels needing escaping.",
                      ("reason",))
    esc.labels(reason='node(s) had taint {k: "v"}, unhandled').inc(3)
    return reg


# ---------------------------------------------------------------- registry ---


def test_counter_get_or_create_and_type_guard():
    reg = Registry()
    a = reg.counter("x_total", "x", ("l",))
    b = reg.counter("x_total", "x again", ("l",))
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("x_total", "now a gauge", ("l",))
    with pytest.raises(ValueError):
        reg.counter("x_total", "other labels", ("other",))


def test_counter_rejects_negative_and_bad_labels():
    reg = Registry()
    c = reg.counter("y_total", "y", ("l",))
    with pytest.raises(ValueError):
        c.labels(l="a").inc(-1)
    with pytest.raises(ValueError):
        c.labels(wrong="a")
    with pytest.raises(ValueError):
        c.inc()  # labeled family needs .labels()


def test_concurrent_increments_from_threads():
    reg = Registry()
    c = reg.counter("t_total", "t")
    h = reg.histogram("t_seconds", "t", buckets=(0.5,))
    child = c.labels()

    def work():
        for _ in range(10_000):
            child.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert child.value == 80_000
    sample = reg.snapshot()["t_seconds"]["samples"][0]
    assert sample["count"] == 80_000
    assert sample["buckets"][0][1] == 80_000  # all in le=0.5
    assert sample["sum"] == pytest.approx(20_000.0)


def test_histogram_bucket_edges_are_inclusive():
    reg = Registry()
    h = reg.histogram("edge_seconds", "e", buckets=(1.0, 2.0))
    h.observe(1.0)   # == bound -> le=1.0 (Prometheus: le is inclusive)
    h.observe(2.0)   # == bound -> le=2.0
    h.observe(2.0001)  # past the last bound -> +Inf only
    s = reg.snapshot()["edge_seconds"]["samples"][0]
    assert s["buckets"] == [[1.0, 1], [2.0, 1], ["+Inf", 1]]
    # rendered counts are CUMULATIVE
    text = reg.render_text()
    assert 'edge_seconds_bucket{le="1"} 1' in text
    assert 'edge_seconds_bucket{le="2"} 2' in text
    assert 'edge_seconds_bucket{le="+Inf"} 3' in text
    assert "edge_seconds_count 3" in text


def test_histogram_rejects_unsorted_buckets():
    reg = Registry()
    with pytest.raises(ValueError):
        reg.histogram("bad", "b", buckets=(2.0, 1.0))


# ----------------------------------------------------------- prometheus text --


def test_prometheus_rendering_matches_golden():
    text = _golden_registry().render_text()
    assert text == GOLDEN.read_text()


def test_snapshot_roundtrips_through_json_to_same_text():
    reg = _golden_registry()
    snap = json.loads(json.dumps(reg.snapshot()))
    assert render_text_from_snapshot(snap) == reg.render_text()


def test_values_flat_view():
    v = _golden_registry().values()
    assert v['demo_requests_total{code="200",verb="GET"}'] == 3
    assert v["demo_queue_depth"] == 8.5
    assert v["demo_latency_seconds_count"] == 7


# -------------------------------------------------------------- chrome trace --


def _make_span_tree():
    with Span("root", log_if_longer=99.0) as root:
        root.step("prep")
        with Span("child", log_if_longer=99.0) as child:
            child.step("inner")
        try:
            with Span("boom", log_if_longer=99.0):
                raise RuntimeError("x")
        except RuntimeError:
            pass
    return root


def test_chrome_trace_roundtrips_through_json():
    root = _make_span_tree()
    assert [c.name for c in root.children] == ["child", "boom"]
    assert root.children[1].failed and not root.children[0].failed

    doc = json.loads(json.dumps(chrome_trace([root], metrics={"m": 1})))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and doc["metadata"]["metrics"] == {"m": 1}
    by_name = {e["name"]: e for e in evs}
    assert {"root", "child", "boom", "prep", "inner"} <= set(by_name)
    for e in evs:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        assert e["pid"] and e["tid"]
    # children nest inside the root's [ts, ts+dur) window
    r = by_name["root"]
    for name in ("child", "boom", "prep"):
        e = by_name[name]
        assert e["ts"] >= r["ts"]
        assert e["ts"] + e["dur"] <= r["ts"] + r["dur"] + 1e-3
    assert by_name["boom"]["args"] == {"failed": True}


# -------------------------------------------------------- engine integration --


def test_engine_emits_core_counters_and_warm_run_adds_no_misses():
    import copy

    from open_simulator_tpu.obs import REGISTRY
    from open_simulator_tpu.simulator.engine import Simulator

    from fixtures import make_node, make_pod

    nodes = [make_node(f"m{i}") for i in range(4)]
    pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(24)]

    def run():
        sim = Simulator(copy.deepcopy(nodes))
        assert sim.schedule_pods(copy.deepcopy(pods)) == []

    def total(values, prefix):
        return sum(v for k, v in values.items() if k.startswith(prefix))

    v0 = REGISTRY.values()
    run()
    v1 = REGISTRY.values()
    run()
    v2 = REGISTRY.values()

    att = "simon_scheduling_attempts_total"
    assert total(v1, att) - total(v0, att) == len(pods)
    assert total(v2, att) - total(v1, att) == len(pods)
    miss = "simon_compile_cache_misses_total"
    assert total(v2, miss) == total(v1, miss), \
        "identical warm run must not register new compile shape buckets"
    assert total(v2, "simon_commits_total") - total(v1, "simon_commits_total") \
        == len(pods)
    assert total(v2, "simon_device_transfer_bytes_total") > 0
    assert total(v2, "simon_segments_total") > total(v1, "simon_segments_total")


def test_preemption_commits_reconcile_via_rollbacks():
    """The rewind/replay pass re-commits pods and evictions remove committed
    pods; commits - rollbacks - victims must equal the placements actually
    materialized on cluster state."""
    from open_simulator_tpu.obs import REGISTRY
    from open_simulator_tpu.simulator.engine import Simulator

    from fixtures import make_node, make_pod

    def prio_pod(name, prio, cpu="1"):
        p = make_pod(name, cpu=cpu, memory="128Mi")
        p["spec"]["priority"] = prio
        return p

    nodes = [make_node("n0", cpu="4")]
    pods = [prio_pod(f"low{i}", 0) for i in range(4)] + [
        prio_pod("high", 100, cpu="2")]

    def total(values, prefix):
        return sum(v for k, v in values.items() if k.startswith(prefix))

    v0 = REGISTRY.values()
    sim = Simulator(nodes)
    sim.schedule_pods(pods)
    v1 = REGISTRY.values()
    live = sum(len(l) for l in sim.pods_on_node)
    commits = total(v1, "simon_commits_total") - total(v0, "simon_commits_total")
    rollbacks = (total(v1, "simon_commit_rollbacks_total")
                 - total(v0, "simon_commit_rollbacks_total"))
    victims = (total(v1, "simon_preemption_victims_total")
               - total(v0, "simon_preemption_victims_total"))
    assert rollbacks > 0  # the preemption pass rewound at least once
    assert victims == len(sim.preempted) > 0
    assert commits - rollbacks - victims == live
    assert (total(v1, "simon_preemption_attempts_total")
            - total(v0, "simon_preemption_attempts_total")) >= 1
