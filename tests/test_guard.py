"""simonguard tests: mid-run device-failure containment.

Covers the four containment behaviors end to end against the real engine:
OOM batch bisection (split-vs-unsplit placements bit-identical, odd sizes
included, floor-hit structured failure), watchdog wedge → quarantine → CPU
failover resuming from the committed prefix, the crash-consistent
capacity-search journal (resume skips completed probes; digest mismatch
rejected; torn tails ignored), the probe-cooldown persistence, and the
preemption replay cap."""

import copy
import json
import time

import pytest

from open_simulator_tpu.apply.applier import CapacityPlanner
from open_simulator_tpu.obs import REGISTRY
from open_simulator_tpu.resilience import (
    Deadline,
    DeadlineExceeded,
    FaultPlan,
    FaultSpec,
    JournalMismatch,
    OOMBisectionExhausted,
    SearchJournal,
    installed,
)
from open_simulator_tpu.resilience import guard
from open_simulator_tpu.simulator.encode import scheduling_signature
from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod


@pytest.fixture(autouse=True)
def _clean_guard_state():
    guard.reset_for_tests()
    yield
    guard.reset_for_tests()


def _census(sim):
    out = {}
    for i, nps in enumerate(sim.pods_on_node):
        for p in nps:
            k = (i, scheduling_signature(p))
            out[k] = out.get(k, 0) + 1
    return out


def _metric(prefix):
    return sum(v for k, v in REGISTRY.values().items() if k.startswith(prefix))


def _cluster(n_nodes=6, n_pods=17):
    nodes = [make_node(f"n{i}", cpu="4000m", memory=str(8 << 30), pods="20")
             for i in range(n_nodes)]
    pods = [make_pod(f"p{j}", cpu="300m", memory=str(256 << 20),
                     labels={"app": f"a{j % 3}"})
            for j in range(n_pods)]
    return nodes, pods


# ------------------------------------------------------------ OOM bisection --


@pytest.mark.parametrize("site", ["oom_dispatch", "oom_to_device"])
@pytest.mark.parametrize("n_pods", [16, 17])  # even and odd batch sizes
def test_oom_bisection_bit_identity(site, n_pods):
    """An injected device OOM splits the batch in halves; the split run's
    placements are bit-identical to the unsplit fault-free run."""
    nodes, pods = _cluster(n_pods=n_pods)
    sim0 = Simulator(copy.deepcopy(nodes))
    failed0 = sim0.schedule_pods(copy.deepcopy(pods))
    baseline = _census(sim0)

    sim = Simulator(copy.deepcopy(nodes))
    p = copy.deepcopy(pods)
    b0 = _metric("simon_guard_oom_bisections_total")
    with installed(FaultPlan([FaultSpec(site, 1)])):
        failed = sim.schedule_pods(p)
    assert _census(sim) == baseline
    assert len(failed) == len(failed0)
    assert _metric("simon_guard_oom_bisections_total") > b0
    # the containment is visible, not silent
    assert any(e[0] == "oom_bisect" for e in guard.events())
    # bisection contains without a failover: the run stays on its backend
    assert sim.backend_path == ["cpu"]


def test_oom_bisection_nested_split():
    """An OOM that re-fires inside the first half forces a nested split —
    still bit-identical."""
    nodes, pods = _cluster(n_pods=17)
    sim0 = Simulator(copy.deepcopy(nodes))
    sim0.schedule_pods(copy.deepcopy(pods))
    baseline = _census(sim0)

    sim = Simulator(copy.deepcopy(nodes))
    with installed(FaultPlan([FaultSpec("oom_dispatch", 1),
                              FaultSpec("oom_dispatch", 2)])):
        sim.schedule_pods(copy.deepcopy(pods))
    assert _census(sim) == baseline
    assert sum(1 for e in guard.events() if e[0] == "oom_bisect") == 2


def test_oom_floor_hit_structured_failure():
    """OOM persisting down to the floor — and through the CPU failover —
    surfaces as OOMBisectionExhausted with a clean rollback."""
    nodes, pods = _cluster(n_nodes=4, n_pods=8)
    sim = Simulator(copy.deepcopy(nodes))
    p = copy.deepcopy(pods)
    pre = copy.deepcopy(p)
    plan = FaultPlan([FaultSpec("oom_dispatch", k) for k in range(1, 200)])
    with installed(plan):
        with pytest.raises(OOMBisectionExhausted) as ei:
            sim.schedule_pods(p)
    assert ei.value.batch == ei.value.floor == 1
    assert p == pre, "rollback left pod-dict residue"
    assert all(not l for l in sim.pods_on_node), "rollback left census residue"
    # the failed-over attempts are on record
    assert sim.backend_path.count("cpu") >= 2


# ------------------------------------------------------- wedge and failover --


def test_watchdog_wedge_failover_resumes_from_committed_prefix():
    """A wedge in the SECOND schedule call must not disturb the first call's
    committed placements: the transaction rolls back only the failing call,
    and the CPU replay converges to the fault-free final state."""
    nodes, pods = _cluster(n_pods=16)
    first, second = pods[:7], pods[7:]

    sim0 = Simulator(copy.deepcopy(nodes))
    sim0.schedule_pods(copy.deepcopy(first))
    committed = _census(sim0)
    sim0.schedule_pods(copy.deepcopy(second))
    baseline = _census(sim0)

    f0 = _metric("simon_guard_failovers_total")
    sim = Simulator(copy.deepcopy(nodes))
    sim.schedule_pods(copy.deepcopy(first))
    assert _census(sim) == committed
    with installed(FaultPlan([FaultSpec("watchdog_wedge", 1)])):
        sim.schedule_pods(copy.deepcopy(second))
    assert _census(sim) == baseline
    assert sim.backend_path == ["cpu", "cpu"]  # initial backend, then failover
    assert guard.quarantined(), "wedge must quarantine the backend"
    assert _metric("simon_guard_failovers_total") > f0
    kinds = [e[0] for e in guard.events()]
    assert kinds == ["wedge", "failover"]


def test_quarantine_routes_later_simulators_to_fallback():
    nodes, pods = _cluster(n_pods=8)
    sim = Simulator(copy.deepcopy(nodes))
    with installed(FaultPlan([FaultSpec("watchdog_wedge", 1)])):
        sim.schedule_pods(copy.deepcopy(pods))
    assert guard.quarantined()
    # a later simulator starts directly on the fallback: no new failover
    f0 = _metric("simon_guard_failovers_total")
    sim2 = Simulator(copy.deepcopy(nodes))
    sim2.schedule_pods(copy.deepcopy(pods))
    assert sim2.backend_path == ["cpu"]
    assert _metric("simon_guard_failovers_total") == f0
    assert _census(sim2) == _census(sim)


def test_supervised_real_timeout_declares_wedge(monkeypatch):
    monkeypatch.setenv("OPEN_SIMULATOR_WATCHDOG_BASE_S", "0.2")
    monkeypatch.setenv("OPEN_SIMULATOR_WATCHDOG_PER_POD_S", "0")
    with pytest.raises(guard.BackendWedged):
        guard.supervised(lambda: time.sleep(3), site="dispatch", pods=0)
    assert "cpu" in guard.quarantined()


def test_real_wedge_quarantine_lifts_after_successful_reprobe(monkeypatch):
    """A REAL watchdog expiry (e.g. one slow compile outlier) must not pin
    the process to CPU forever: past the re-probe window, one bounded
    BACKGROUND subprocess probe that finds the backend responsive lifts the
    quarantine — and a lift that fails to stick (a second real wedge) makes
    the re-quarantine permanent, bounding the lift/burn cycle at one."""
    import open_simulator_tpu.utils.devices as devices

    monkeypatch.setenv("OPEN_SIMULATOR_WATCHDOG_BASE_S", "0.2")
    monkeypatch.setenv("OPEN_SIMULATOR_WATCHDOG_PER_POD_S", "0")
    monkeypatch.setenv("OPEN_SIMULATOR_QUARANTINE_REPROBE_S", "0.01")
    probes = []
    monkeypatch.setattr(devices, "probe_default_backend",
                        lambda *a, **k: (probes.append(1) or True,
                                         {"outcome": "ok"}))
    with pytest.raises(guard.BackendWedged):
        guard.supervised(lambda: time.sleep(3), site="dispatch", pods=0)
    assert "cpu" in guard.quarantined()
    time.sleep(0.05)  # past the re-probe window
    guard.default_quarantined()  # kicks off the async re-probe; never blocks
    deadline = time.monotonic() + 5.0
    while guard.quarantined() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert guard.quarantined() == {}, "responsive backend must be lifted"
    assert probes, "the lift must come from an actual re-probe"
    assert any(e[0] == "unquarantine" for e in guard.events())

    # the lift did not stick: a SECOND real wedge re-quarantines PERMANENTLY
    # (the subprocess probe demonstrably cannot see this process's state)
    with pytest.raises(guard.BackendWedged):
        guard.supervised(lambda: time.sleep(3), site="dispatch", pods=0)
    assert "cpu" in guard.quarantined()
    n_probes = len(probes)
    time.sleep(0.05)
    assert guard.default_quarantined()
    time.sleep(0.05)
    assert guard.default_quarantined(), "re-quarantine must be permanent"
    assert len(probes) == n_probes, "a permanent quarantine never re-probes"


def test_injected_wedge_quarantine_never_reprobes(monkeypatch):
    """Injected wedges stay deterministically quarantined — the fault-smoke
    replay-equality criterion must never depend on a live probe."""
    import open_simulator_tpu.utils.devices as devices

    monkeypatch.setenv("OPEN_SIMULATOR_QUARANTINE_REPROBE_S", "0.01")

    def _no_probe(*a, **k):
        raise AssertionError("injected quarantine must not probe")

    monkeypatch.setattr(devices, "probe_default_backend", _no_probe)
    with installed(FaultPlan([FaultSpec("watchdog_wedge", 1)])):
        with pytest.raises(guard.BackendWedged):
            guard.supervised(lambda: None, site="dispatch", pods=0)
    time.sleep(0.05)
    assert guard.default_quarantined()
    assert guard.quarantined()


def test_supervised_prefers_deadline_over_wedge(monkeypatch):
    """When the CALLER's Deadline expires during the wait, that is a budget
    expiry, not a device wedge: no quarantine."""
    monkeypatch.setenv("OPEN_SIMULATOR_WATCHDOG_BASE_S", "30")
    with Deadline(0.15):
        with pytest.raises(DeadlineExceeded):
            guard.supervised(lambda: time.sleep(3), site="dispatch", pods=0)
    assert guard.quarantined() == {}


def test_supervised_reraises_worker_errors_transparently():
    with pytest.raises(ZeroDivisionError):
        guard.supervised(lambda: 1 // 0, site="dispatch", pods=0)


def test_backend_path_on_simulate_result():
    from open_simulator_tpu.core.types import ResourceTypes
    from open_simulator_tpu.simulator.core import simulate

    nodes, pods = _cluster(n_pods=4)
    res = simulate(ResourceTypes(nodes=nodes, pods=pods), [])
    assert res.backend_path == ["cpu"]


# ----------------------------------------------------- capacity-search journal


def _planner_inputs():
    """lb-inexact fragmentation workload: 10 pods of 3000m on 4000m nodes —
    the arithmetic bound says 6 added nodes, the truth is 8, so the search
    runs several probe rounds (a journal with real content)."""
    base = [make_node(f"b{i}", cpu="4000m", memory=str(8 << 30), pods="20")
            for i in range(2)]
    template = make_node("tmpl", cpu="4000m", memory=str(8 << 30), pods="20")
    pods = [make_pod(f"w{j}", cpu="3000m", memory=str(128 << 20))
            for j in range(10)]
    return base, template, pods


def test_journal_resume_skips_completed_probes(tmp_path):
    path = str(tmp_path / "search.jsonl")
    base, template, pods = _planner_inputs()

    p1 = CapacityPlanner(base, template, copy.deepcopy(pods))
    p1.attach_journal(path)
    found1, n1, _ = p1.search()
    assert found1 and p1.stats["dispatches"] > 0

    assert p1.journal._f is None, "search must close the journal fd"

    p2 = CapacityPlanner(base, template, copy.deepcopy(pods))
    p2.attach_journal(path)
    found2, n2, _ = p2.search()
    assert (found2, n2) == (found1, n1)
    assert p2.stats["dispatches"] == 0, \
        "a fully journaled search must not re-run any probe"
    assert p2.stats["journal_hits"] > 0

    # no-journal control: same answer
    p3 = CapacityPlanner(base, template, copy.deepcopy(pods))
    found3, n3, _ = p3.search()
    assert (found3, n3) == (found1, n1)


def test_reused_planner_keeps_journaling_after_close(tmp_path):
    """search() closes the journal fd when it finishes; a REUSED planner's
    next search must keep journaling (append to the valid file), not crash
    on the closed handle."""
    path = str(tmp_path / "search.jsonl")
    base, template, pods = _planner_inputs()
    p = CapacityPlanner(base, template, copy.deepcopy(pods))
    p.attach_journal(path)
    found1, n1, _ = p.search()
    found2, n2, _ = p.search()  # second search on the SAME planner
    assert (found2, n2) == (found1, n1)
    # and a journal record on the reused planner hits disk, fsync'd
    p.journal.record(999, True, 0)
    assert SearchJournal.open(path, p.options_digest()).lookup(999) == (True, 0)


def test_journal_digest_mismatch_rejected(tmp_path):
    path = str(tmp_path / "search.jsonl")
    base, template, pods = _planner_inputs()
    p1 = CapacityPlanner(base, template, copy.deepcopy(pods))
    p1.attach_journal(path)
    p1.search()
    # a DIFFERENT search (one more pod) must refuse the stale journal
    other = copy.deepcopy(pods) + [make_pod("extra", cpu="3000m",
                                            memory=str(128 << 20))]
    p2 = CapacityPlanner(base, template, other)
    with pytest.raises(JournalMismatch):
        p2.attach_journal(path)
    # same names but materially different cluster (node allocatable shrunk)
    # must ALSO refuse: the digest covers object contents, not identities
    base2 = copy.deepcopy(base)
    base2[0]["status"]["allocatable"]["cpu"] = "2000m"
    p3 = CapacityPlanner(base2, template, copy.deepcopy(pods))
    with pytest.raises(JournalMismatch):
        p3.attach_journal(path)
    # and a same-name pod with different requests
    pods2 = copy.deepcopy(pods)
    pods2[0]["spec"]["containers"][0]["resources"]["requests"]["cpu"] = "100m"
    p4 = CapacityPlanner(base, template, pods2)
    with pytest.raises(JournalMismatch):
        p4.attach_journal(path)


def test_journal_write_fault_leaves_resumable_prefix(tmp_path):
    path = str(tmp_path / "search.jsonl")
    base, template, pods = _planner_inputs()

    p0 = CapacityPlanner(base, template, copy.deepcopy(pods))
    found0, n0, _ = p0.search()  # fault-free answer

    p1 = CapacityPlanner(base, template, copy.deepcopy(pods))
    p1.attach_journal(path)
    with installed(FaultPlan([FaultSpec("journal_write", 2)])):
        with pytest.raises(Exception):
            p1.search()

    # the journal's valid prefix survives and resumes to the same answer
    p2 = CapacityPlanner(base, template, copy.deepcopy(pods))
    p2.attach_journal(path)
    found2, n2, _ = p2.search()
    assert (found2, n2) == (found0, n0)
    assert p2.stats["journal_hits"] >= 1


def test_journal_ignores_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = SearchJournal.open(path, "sha256:x")
    j.record(3, False, 2)
    j.close()
    with open(path, "a") as f:
        f.write('{"n": 9, "ok"')  # SIGKILL mid-write
    j2 = SearchJournal.open(path, "sha256:x")
    assert j2.lookup(3) == (False, 2)
    assert j2.lookup(9) is None
    j2.record(9, True, 0)  # and stays appendable
    j2.close()
    assert SearchJournal.open(path, "sha256:x").lookup(9) == (True, 0)


def test_journal_torn_tail_with_invalid_utf8_truncates_byte_exact(tmp_path):
    """A SIGKILL can tear a write at any byte, leaving invalid utf-8 in the
    tail; the repair must truncate at the BYTE offset of the valid prefix
    (a replace-decoded round trip would widen each bad byte to a 3-byte
    U+FFFD and overshoot)."""
    path = str(tmp_path / "j.jsonl")
    j = SearchJournal.open(path, "sha256:x")
    j.record(3, False, 2)
    j.close()
    import os as _os

    good = _os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b'{"n": 9, "ok\xff\xfe\xfd')  # torn mid-write, non-utf8 bytes
    j2 = SearchJournal.open(path, "sha256:x")
    assert j2.lookup(3) == (False, 2)
    assert j2.lookup(9) is None
    assert _os.path.getsize(path) == good, "repair must cut exactly the tail"
    j2.record(9, True, 0)
    j2.close()
    with open(path, "rb") as f:
        for line in f.read().splitlines():  # no garbage survived the repair
            json.loads(line)
    assert SearchJournal.open(path, "sha256:x").lookup(9) == (True, 0)


def test_journal_torn_header_treated_as_empty(tmp_path):
    """A crash mid-HEADER-write leaves an unterminated PREFIX of the header
    this search would write: no verdict can follow it, so the journal is
    empty — resume rewrites it instead of failing with JournalMismatch and
    demanding manual deletion."""
    path = str(tmp_path / "j.jsonl")
    full_header = json.dumps(
        {"kind": SearchJournal.KIND, "v": SearchJournal.VERSION,
         "digest": "sha256:x"}, sort_keys=True)
    with open(path, "w") as f:
        f.write(full_header[:-7])  # SIGKILL mid-header (no newline)
    j = SearchJournal.open(path, "sha256:x")
    assert j.verdicts == {}
    j.record(1, True, 0)
    j.close()
    assert SearchJournal.open(path, "sha256:x").lookup(1) == (True, 0)
    # a TERMINATED non-journal first line is a different file, not a torn
    # header: still rejected
    with open(path, "w") as f:
        f.write("not a journal at all\n")
    with pytest.raises(JournalMismatch):
        SearchJournal.open(path, "sha256:x")
    # an UNTERMINATED line that is NOT a prefix of this search's header is
    # someone else's file (typo'd --resume-journal path, another search's
    # torn header): rejected UNTOUCHED, never clobbered
    with open(path, "w") as f:
        f.write("v1.2.3-some-users-version-file")  # no trailing newline
    with pytest.raises(JournalMismatch):
        SearchJournal.open(path, "sha256:x")
    with open(path) as f:
        assert f.read() == "v1.2.3-some-users-version-file", \
            "a rejected file must not be modified"
    # ...including a torn header from a DIFFERENT search's digest
    other = json.dumps(
        {"kind": SearchJournal.KIND, "v": SearchJournal.VERSION,
         "digest": "sha256:OTHER"}, sort_keys=True)
    with open(path, "w") as f:
        f.write(other[:-7])
    with pytest.raises(JournalMismatch):
        SearchJournal.open(path, "sha256:x")


def test_probe_session_build_declines_on_quarantined_backend():
    """A session built after quarantine would upload device tables to the
    wedged backend (this path has no fallback routing): try_build must
    decline so the search runs fresh, CPU-routed probes."""
    from open_simulator_tpu.simulator.probe import ProbeSession

    base, template, pods = _planner_inputs()
    guard.quarantine("cpu", "watchdog_wedge@dispatch")
    assert ProbeSession.try_build(base, template, copy.deepcopy(pods)) is None
    p = CapacityPlanner(base, template, copy.deepcopy(pods))
    found, n, _ = p.search()
    assert found and p.stats["path"] == "fresh"


def test_probe_session_refuses_dispatch_after_midlife_quarantine():
    """A session whose tables were uploaded BEFORE another simulator
    quarantined the backend must not re-dispatch on it (committed arrays
    override jax.default_device): the containable wedge classification
    surfaces immediately instead of burning a watchdog timeout."""
    from open_simulator_tpu.simulator.probe import ProbeSession

    base, template, pods = _planner_inputs()
    session = ProbeSession.try_build(base, template, copy.deepcopy(pods))
    assert session is not None
    guard.quarantine("cpu", "watchdog_wedge@dispatch")
    with pytest.raises(guard.BackendWedged):
        session.probe_many([1])
    with pytest.raises(guard.BackendWedged):
        session.ensure_capacity(session.n_new + 1)


def test_search_contains_wedge_by_falling_back_to_fresh_probes():
    """A wedge mid-incremental-search is contained: the search falls back to
    fresh probes (on the quarantine-routed backend) and finds the same n."""
    base, template, pods = _planner_inputs()
    p0 = CapacityPlanner(base, template, copy.deepcopy(pods))
    found0, n0, _ = p0.search()
    assert p0.stats["path"] == "incremental"

    guard.reset_for_tests()
    p1 = CapacityPlanner(base, template, copy.deepcopy(pods))
    with installed(FaultPlan([FaultSpec("watchdog_wedge", 1)])):
        found1, n1, _ = p1.search()
    assert (found1, n1) == (found0, n0)
    assert p1.stats["path"] == "fresh"
    assert any(e[0] == "failover" and e[2] == "capacity_search"
               for e in guard.events())


# ------------------------------------------------------- probe cooldown ------


def test_probe_cooldown_short_circuits_known_wedge(tmp_path, monkeypatch):
    from open_simulator_tpu.utils.devices import probe_default_backend

    state = str(tmp_path / "probe_state.json")
    monkeypatch.setenv("OPEN_SIMULATOR_PROBE_COOLDOWN_S", "600")
    with open(state, "w") as f:
        json.dump({"ts_epoch": time.time(), "outcome": "timeout"}, f)
    t0 = time.perf_counter()
    ok, rec = probe_default_backend(timeout=30.0, state_path=state)
    assert not ok
    assert rec["outcome"] == "cooldown"
    assert rec["last_outcome"] == "timeout"
    assert time.perf_counter() - t0 < 1.0, "cooldown hit must not probe"


def test_probe_cooldown_expired_state_does_not_short_circuit(tmp_path, monkeypatch):
    """An old wedge record is past the window: the probe must actually run
    (observable as the state file being rewritten with a fresh outcome)."""
    from open_simulator_tpu.utils.devices import probe_default_backend

    state = str(tmp_path / "probe_state.json")
    monkeypatch.setenv("OPEN_SIMULATOR_PROBE_COOLDOWN_S", "1")
    with open(state, "w") as f:
        json.dump({"ts_epoch": time.time() - 3600, "outcome": "timeout"}, f)
    ok, rec = probe_default_backend(timeout=120.0, state_path=state)
    assert rec["outcome"] != "cooldown"
    with open(state) as f:
        st = json.load(f)
    assert st["outcome"] == rec["outcome"]


# ---------------------------------------------------- preemption replay cap --


def _preempt_cluster():
    node = make_node("n1", cpu="2000m", memory=str(4 << 30), pods="10")
    pods = [make_pod("low-0", cpu="900m", memory=str(1 << 30)),
            make_pod("low-1", cpu="900m", memory=str(1 << 30)),
            make_pod("high-0", cpu="1800m", memory=str(2 << 30))]
    pods[2]["spec"]["priority"] = 100
    return [node], pods


def test_preemption_replay_cap_skips_attempts(monkeypatch):
    monkeypatch.setenv("OPEN_SIMULATOR_MAX_PREEMPTION_REPLAYS", "0")
    nodes, pods = _preempt_cluster()
    c0 = _metric("simon_preemption_attempts_total")
    sim = Simulator(copy.deepcopy(nodes))
    failed = sim.schedule_pods(copy.deepcopy(pods))
    assert sim.preempted == [], "capped run must not evict"
    assert len(failed) == 1  # the high-prio pod stays failed, conservatively
    snap = REGISTRY.values()
    capped = sum(v for k, v in snap.items()
                 if k.startswith("simon_preemption_attempts_total")
                 and 'outcome="capped"' in k)
    assert capped >= 1
    assert _metric("simon_preemption_attempts_total") > c0


def test_preemption_uncapped_still_preempts(monkeypatch):
    monkeypatch.setenv("OPEN_SIMULATOR_MAX_PREEMPTION_REPLAYS", "512")
    nodes, pods = _preempt_cluster()
    sim = Simulator(copy.deepcopy(nodes))
    sim.schedule_pods(copy.deepcopy(pods))
    assert len(sim.preempted) == 2
