"""Wave scheduling == serial scan, placement for placement.

The wave kernel (ops/kernels.py schedule_wave) must reproduce the serial
one-pod-per-scan-step process exactly: every test runs the same pod sequence
through a waves-on and a waves-off Simulator and compares the per-(node,
workload) placement census and the per-group failure counts. Pods within one
scheduling group are interchangeable (the reference's selectHost tie-break is
random anyway, generic_scheduler.go:188), so the census — not pod names — is
the equality that matters.
"""

import copy

import pytest

from open_simulator_tpu.core import constants as C
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.utils.objutil import annotations_of, labels_of, name_of

from fixtures import make_node, make_pod, master_taint, master_toleration


def census_of(sim: Simulator):
    out = {}
    for i, pods in enumerate(sim.pods_on_node):
        for p in pods:
            key = (i, labels_of(p).get("app") or name_of(p))
            out[key] = out.get(key, 0) + 1
    return out


def run_both(nodes, batches):
    """batches: list of pod lists scheduled via consecutive schedule_pods calls.
    Returns (wave_census, serial_census, wave_failed, serial_failed)."""
    results = []
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes))
        failed = []
        for batch in batches:
            failed.extend(sim.schedule_pods(copy.deepcopy(batch)))
        fail_count = {}
        for up in failed:
            key = labels_of(up.pod).get("app") or name_of(up.pod)
            fail_count[key] = fail_count.get(key, 0) + 1
        results.append((census_of(sim), fail_count))
    (wc, wf), (sc, sf) = results
    return wc, sc, wf, sf


def replicas(name, n, start=0, **kw):
    kw.setdefault("labels", {"app": name})
    return [make_pod(f"{name}-{i}", **kw) for i in range(start, start + n)]


def anti_affinity(app):
    return {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": app}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }


def test_wave_homogeneous_big_run():
    nodes = [make_node(f"n{i}", cpu="16", memory="32Gi") for i in range(12)]
    pods = replicas("web", 150, cpu="500m", memory="512Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf == {}
    assert sum(wc.values()) == 150


def test_wave_heterogeneous_nodes_and_exhaustion():
    # mixed capacities; pods overflow the cluster so the tail fails — the wave
    # path must fail the same NUMBER per group as serial
    nodes = (
        [make_node(f"big{i}", cpu="16", memory="32Gi") for i in range(3)]
        + [make_node(f"mid{i}", cpu="8", memory="8Gi") for i in range(4)]
        + [make_node(f"small{i}", cpu="2", memory="2Gi") for i in range(5)]
    )
    pods = replicas("fat", 80, cpu="2", memory="3Gi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc
    assert wf == sf
    assert wf.get("fat", 0) > 0  # the scenario actually overflows


def test_wave_taints_selectors_and_preferred_affinity():
    nodes = [
        make_node("master-1", taints=[master_taint()]),
        make_node("master-2", taints=[master_taint()]),
        make_node("gpuish-1", labels={"disk": "ssd", "zone-ish": "a"}),
        make_node("gpuish-2", labels={"disk": "ssd", "zone-ish": "b"}),
        make_node("plain-1"),
        make_node("plain-2", cpu="4", memory="4Gi"),
    ]
    pref = {
        "nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 10,
                 "preference": {"matchExpressions": [
                     {"key": "disk", "operator": "In", "values": ["ssd"]}]}}
            ]
        }
    }
    batches = [
        replicas("tol", 16, cpu="200m", memory="256Mi",
                 tolerations=[master_toleration()]),
        replicas("ssdlover", 24, cpu="250m", memory="256Mi", affinity=pref),
        replicas("picky", 12, cpu="100m", memory="128Mi",
                 node_selector={"disk": "ssd"}),
    ]
    wc, sc, wf, sf = run_both(nodes, batches)
    assert wc == sc and wf == sf


def test_wave_hostname_anti_affinity_cap1():
    nodes = [make_node(f"n{i}") for i in range(10)]
    pods = replicas("spread", 14, cpu="100m", memory="128Mi",
                    affinity=anti_affinity("spread"))
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc
    # at most one per node; 4 pods cannot land
    assert all(v == 1 for v in wc.values())
    assert wf == sf == {"spread": 4}


def test_wave_anti_affinity_against_seeded_pods():
    # nodes already hosting app=spread pods are blocked from the start
    nodes = [make_node(f"n{i}") for i in range(6)]
    seed = [make_pod("pre-0", labels={"app": "spread"}, node_name="n2"),
            make_pod("pre-1", labels={"app": "spread"}, node_name="n4")]
    pods = replicas("spread", 6, cpu="100m", memory="128Mi",
                    affinity=anti_affinity("spread"))
    wc, sc, wf, sf = run_both(nodes, [seed, pods])
    assert wc == sc and wf == sf
    assert wf == {"spread": 2}  # 6 nodes - 2 seeded = 4 free slots


def test_wave_mixed_eligible_and_ineligible_runs():
    # hostPort pods are serial-only; they interleave with two eligible runs and
    # contend for the same capacity
    nodes = [make_node(f"n{i}", cpu="4", memory="8Gi") for i in range(8)]
    a = replicas("alpha", 24, cpu="300m", memory="512Mi")
    b = replicas("porty", 6, cpu="300m", memory="512Mi", host_ports=[8080])
    c = replicas("omega", 24, cpu="300m", memory="512Mi")
    wc, sc, wf, sf = run_both(nodes, [a + b + c])
    assert wc == sc and wf == sf


def test_wave_pod_affinity_to_other_group():
    # required pod affinity whose selector matches a DIFFERENT app: the counter
    # never matches the group itself, so the run stays wave-eligible
    nodes = [make_node(f"n{i}") for i in range(6)]
    anchors = [make_pod("anchor-0", labels={"app": "anchor"}, node_name="n1"),
               make_pod("anchor-1", labels={"app": "anchor"}, node_name="n3")]
    aff = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"labelSelector": {"matchLabels": {"app": "anchor"}},
                 "topologyKey": "kubernetes.io/hostname"}
            ]
        }
    }
    pods = replicas("follower", 12, cpu="100m", memory="128Mi", affinity=aff)
    wc, sc, wf, sf = run_both(nodes, [anchors, pods])
    assert wc == sc and wf == sf
    landed = {k[0] for k, v in wc.items() if k[1] == "follower"}
    assert landed <= {1, 3}


def test_wave_small_runs_stay_serial():
    # runs below WAVE_MIN ride the scan; behavior identical either way
    nodes = [make_node(f"n{i}") for i in range(4)]
    batches = [replicas(f"app{k}", 3, cpu="200m", memory="256Mi") for k in range(5)]
    wc, sc, wf, sf = run_both(nodes, [sum(batches, [])])
    assert wc == sc and wf == sf


def test_wave_depth_truncation_flat_scores():
    # one huge node whose score column is flat far beyond the kernel's table
    # depth (WAVE_BLOCK), next to small nodes: serial keeps filling the huge
    # node past depth-B, so the wave must not fall back to the small nodes'
    # lower-scored entries (the hidden-continuation guard)
    nodes = [make_node("huge", cpu="2000", memory="4000Gi", pods="5000")] + [
        make_node(f"small{i}", cpu="2", memory="2Gi") for i in range(4)
    ]
    pods = replicas("tiny", 400, cpu="10m", memory="16Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_wave_two_flat_columns_tie():
    # two equally huge nodes with identical flat columns: serial alternates on
    # integer score drops with lowest-index tie-break; waves must reproduce it
    nodes = [make_node(f"huge{i}", cpu="1000", memory="2000Gi", pods="4000")
             for i in range(2)]
    pods = replicas("tiny", 500, cpu="10m", memory="16Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_wave_segments_split():
    # direct check of the segmentation: eligible big run + tiny run + forced pod
    nodes = [make_node(f"n{i}") for i in range(4)]
    sim = Simulator(copy.deepcopy(nodes))
    pods = (replicas("big", 10, cpu="100m", memory="128Mi")
            + replicas("tiny", 2, cpu="100m", memory="128Mi"))
    bt = sim.encode_batch(copy.deepcopy(pods))
    segs = sim._segments(bt, len(pods))
    kinds = [s[0] for s in segs]
    assert kinds == ["wave", "serial"]
    assert segs[0][1:3] == (0, 10)
    assert segs[1][1:3] == (10, 2)
