"""Wave scheduling == serial scan, placement for placement.

The wave kernel (ops/kernels.py schedule_wave) must reproduce the serial
one-pod-per-scan-step process exactly: every test runs the same pod sequence
through a waves-on and a waves-off Simulator and compares the per-(node,
workload) placement census and the per-group failure counts. Pods within one
scheduling group are interchangeable (the reference's selectHost tie-break is
random anyway, generic_scheduler.go:188), so the census — not pod names — is
the equality that matters.
"""

import copy

import pytest

from open_simulator_tpu.core import constants as C
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.utils.objutil import annotations_of, labels_of, name_of

from fixtures import make_node, make_pod, master_taint, master_toleration


def census_of(sim: Simulator):
    # keyed by scheduling signature, not app label: constraint-distinct pods
    # sharing one label must count as disagreements when the paths swap them
    from open_simulator_tpu.simulator.encode import scheduling_signature

    out = {}
    for i, pods in enumerate(sim.pods_on_node):
        for p in pods:
            key = (i, scheduling_signature(p))
            out[key] = out.get(key, 0) + 1
    return out


def run_both(nodes, batches, extract=None, services=None):
    """batches: list of pod lists scheduled via consecutive schedule_pods calls.
    Returns (wave_census, serial_census, wave_failed, serial_failed) plus, when
    `extract` is given, its per-sim result appended for each path."""
    results = []
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes))
        sim.use_waves = waves
        if services:
            from open_simulator_tpu.core.types import ResourceTypes

            sim.register_cluster_objects(
                ResourceTypes(services=copy.deepcopy(services)))
        failed = []
        for batch in batches:
            failed.extend(sim.schedule_pods(copy.deepcopy(batch)))
        fail_count = {}
        for up in failed:
            key = labels_of(up.pod).get("app") or name_of(up.pod)
            fail_count[key] = fail_count.get(key, 0) + 1
        results.append((census_of(sim), fail_count, extract(sim) if extract else None))
    (wc, wf, wx), (sc, sf, sx) = results
    if extract is None:
        return wc, sc, wf, sf
    return wc, sc, wf, sf, wx, sx


def replicas(name, n, start=0, **kw):
    kw.setdefault("labels", {"app": name})
    return [make_pod(f"{name}-{i}", **kw) for i in range(start, start + n)]


def anti_affinity(app):
    return {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": app}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }


def test_wave_homogeneous_big_run():
    nodes = [make_node(f"n{i}", cpu="16", memory="32Gi") for i in range(12)]
    pods = replicas("web", 150, cpu="500m", memory="512Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf == {}
    assert sum(wc.values()) == 150


def test_wave_heterogeneous_nodes_and_exhaustion():
    # mixed capacities; pods overflow the cluster so the tail fails — the wave
    # path must fail the same NUMBER per group as serial
    nodes = (
        [make_node(f"big{i}", cpu="16", memory="32Gi") for i in range(3)]
        + [make_node(f"mid{i}", cpu="8", memory="8Gi") for i in range(4)]
        + [make_node(f"small{i}", cpu="2", memory="2Gi") for i in range(5)]
    )
    pods = replicas("fat", 80, cpu="2", memory="3Gi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc
    assert wf == sf
    assert wf.get("fat", 0) > 0  # the scenario actually overflows


def test_wave_taints_selectors_and_preferred_affinity():
    nodes = [
        make_node("master-1", taints=[master_taint()]),
        make_node("master-2", taints=[master_taint()]),
        make_node("gpuish-1", labels={"disk": "ssd", "zone-ish": "a"}),
        make_node("gpuish-2", labels={"disk": "ssd", "zone-ish": "b"}),
        make_node("plain-1"),
        make_node("plain-2", cpu="4", memory="4Gi"),
    ]
    pref = {
        "nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 10,
                 "preference": {"matchExpressions": [
                     {"key": "disk", "operator": "In", "values": ["ssd"]}]}}
            ]
        }
    }
    batches = [
        replicas("tol", 16, cpu="200m", memory="256Mi",
                 tolerations=[master_toleration()]),
        replicas("ssdlover", 24, cpu="250m", memory="256Mi", affinity=pref),
        replicas("picky", 12, cpu="100m", memory="128Mi",
                 node_selector={"disk": "ssd"}),
    ]
    wc, sc, wf, sf = run_both(nodes, batches)
    assert wc == sc and wf == sf


def test_wave_hostname_anti_affinity_cap1():
    nodes = [make_node(f"n{i}") for i in range(10)]
    pods = replicas("spread", 14, cpu="100m", memory="128Mi",
                    affinity=anti_affinity("spread"))
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc
    # at most one per node; 4 pods cannot land
    assert all(v == 1 for v in wc.values())
    assert wf == sf == {"spread": 4}


def test_wave_anti_affinity_against_seeded_pods():
    # nodes already hosting app=spread pods are blocked from the start
    nodes = [make_node(f"n{i}") for i in range(6)]
    seed = [make_pod("pre-0", labels={"app": "spread"}, node_name="n2"),
            make_pod("pre-1", labels={"app": "spread"}, node_name="n4")]
    pods = replicas("spread", 6, cpu="100m", memory="128Mi",
                    affinity=anti_affinity("spread"))
    wc, sc, wf, sf = run_both(nodes, [seed, pods])
    assert wc == sc and wf == sf
    assert wf == {"spread": 2}  # 6 nodes - 2 seeded = 4 free slots


def test_wave_mixed_eligible_and_ineligible_runs():
    # hostPort pods are serial-only; they interleave with two eligible runs and
    # contend for the same capacity
    nodes = [make_node(f"n{i}", cpu="4", memory="8Gi") for i in range(8)]
    a = replicas("alpha", 24, cpu="300m", memory="512Mi")
    b = replicas("porty", 6, cpu="300m", memory="512Mi", host_ports=[8080])
    c = replicas("omega", 24, cpu="300m", memory="512Mi")
    wc, sc, wf, sf = run_both(nodes, [a + b + c])
    assert wc == sc and wf == sf


def test_wave_pod_affinity_to_other_group():
    # required pod affinity whose selector matches a DIFFERENT app: the counter
    # never matches the group itself, so the run stays wave-eligible
    nodes = [make_node(f"n{i}") for i in range(6)]
    anchors = [make_pod("anchor-0", labels={"app": "anchor"}, node_name="n1"),
               make_pod("anchor-1", labels={"app": "anchor"}, node_name="n3")]
    aff = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"labelSelector": {"matchLabels": {"app": "anchor"}},
                 "topologyKey": "kubernetes.io/hostname"}
            ]
        }
    }
    pods = replicas("follower", 12, cpu="100m", memory="128Mi", affinity=aff)
    wc, sc, wf, sf = run_both(nodes, [anchors, pods])
    assert wc == sc and wf == sf
    landed = {k[0] for k, v in wc.items() if k[1] == "follower"}
    assert landed <= {1, 3}


def test_wave_small_runs_stay_serial():
    # runs below WAVE_MIN ride the scan; behavior identical either way
    nodes = [make_node(f"n{i}") for i in range(4)]
    batches = [replicas(f"app{k}", 3, cpu="200m", memory="256Mi") for k in range(5)]
    wc, sc, wf, sf = run_both(nodes, [sum(batches, [])])
    assert wc == sc and wf == sf


def test_wave_depth_truncation_flat_scores():
    # one huge node whose score column is flat far beyond the kernel's table
    # depth (WAVE_BLOCK), next to small nodes: serial keeps filling the huge
    # node past depth-B, so the wave must not fall back to the small nodes'
    # lower-scored entries (the hidden-continuation guard)
    nodes = [make_node("huge", cpu="2000", memory="4000Gi", pods="5000")] + [
        make_node(f"small{i}", cpu="2", memory="2Gi") for i in range(4)
    ]
    pods = replicas("tiny", 400, cpu="10m", memory="16Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_wave_two_flat_columns_tie():
    # two equally huge nodes with identical flat columns: serial alternates on
    # integer score drops with lowest-index tie-break; waves must reproduce it
    nodes = [make_node(f"huge{i}", cpu="1000", memory="2000Gi", pods="4000")
             for i in range(2)]
    pods = replicas("tiny", 500, cpu="10m", memory="16Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_wave_segments_split():
    # direct check of the segmentation: eligible big run + tiny run + forced pod
    nodes = [make_node(f"n{i}") for i in range(4)]
    sim = Simulator(copy.deepcopy(nodes))
    pods = (replicas("big", 10, cpu="100m", memory="128Mi")
            + replicas("tiny", 2, cpu="100m", memory="128Mi"))
    bt = sim.encode_batch(copy.deepcopy(pods))
    segs = sim._segments(bt, len(pods))
    kinds = [s[0] for s in segs]
    assert kinds == ["wave", "serial"]
    assert segs[0][1:3] == (0, 10)
    assert segs[1][1:3] == (10, 2)


# ---------------------------------------------------------------- spread waves ----
#
# DoNotSchedule topology-spread groups are wave-eligible via the kernel's live
# filter + inertness cut (schedule_wave dns_live). Every scenario below runs the
# same pods through waves-on and waves-off engines; censuses must match exactly,
# including when the constraint binds hard, when domains are blocked from the
# start, and when the min-domain count rises mid-run.


def spread(app, key="zone", max_skew=1):
    return [{
        "maxSkew": max_skew,
        "topologyKey": key,
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": app}},
    }]


def zoned_nodes(counts, **kw):
    """counts: pods-per-zone node counts, e.g. [4, 2, 1] builds 7 nodes in 3 zones."""
    nodes = []
    for z, c in enumerate(counts):
        for i in range(c):
            nodes.append(make_node(f"z{z}-n{i}", labels={"zone": f"zone-{z}"}, **kw))
    return nodes


def spread_replicas(app, n, max_skew=1, key="zone", start=0, **kw):
    pods = replicas(app, n, start=start, **kw)
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = spread(app, key=key, max_skew=max_skew)
    return pods


def test_spread_wave_balanced_zones():
    nodes = zoned_nodes([3, 3, 3])
    pods = spread_replicas("web", 60, cpu="100m", memory="128Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf == {}


def test_spread_wave_skewed_zones_constraint_binds():
    # zone-2 has one node: once it fills, skew blocks the big zones — the wave
    # must cut exactly where serial's feasible set changes
    nodes = zoned_nodes([6, 3, 1], cpu="4", memory="8Gi")
    pods = spread_replicas("skew", 80, max_skew=1, cpu="200m", memory="256Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    assert sum(wc.values()) < 80  # the single-node zone caps total placements


def test_spread_wave_blocked_at_start_then_min_rise():
    # seed zone-0 far above the others: zone-0 starts blocked and is re-admitted
    # only when the min rises (the (b) cut direction)
    nodes = zoned_nodes([2, 2, 2], cpu="16", memory="32Gi")
    seed = [make_pod(f"seed-{i}", labels={"app": "riser"}, node_name="z0-n0",
                     cpu="100m", memory="128Mi") for i in range(5)]
    for p in seed:
        p["spec"]["topologySpreadConstraints"] = spread("riser")
    pods = spread_replicas("riser", 40, max_skew=2, cpu="100m", memory="128Mi")
    wc, sc, wf, sf = run_both(nodes, [seed, pods])
    assert wc == sc and wf == sf


def test_spread_wave_maxskew_1_tight():
    nodes = zoned_nodes([1, 1, 1, 1], cpu="16", memory="32Gi")
    pods = spread_replicas("tight", 37, max_skew=1, cpu="50m", memory="64Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf == {}


def test_spread_wave_missing_topo_key_nodes():
    # two nodes lack the zone label entirely: they are never eligible domains and
    # the spread filter must keep excluding them on both paths
    nodes = zoned_nodes([2, 2]) + [make_node(f"plain{i}") for i in range(2)]
    pods = spread_replicas("keyed", 30, cpu="100m", memory="128Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    landed_plain = {k for k in wc if k[0] >= 4}
    assert not landed_plain


def test_spread_wave_hostname_key():
    # hostname-keyed spread: every node is its own domain, so maxSkew=1 caps the
    # per-node difference at one — a much larger domain count than zones
    nodes = [make_node(f"n{i}") for i in range(7)]
    pods = spread_replicas("host", 40, key="kubernetes.io/hostname",
                           cpu="100m", memory="128Mi")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_spread_wave_non_self_matching_static():
    # the constraint tracks a DIFFERENT app: counters never move during the run,
    # so the group rides the plain (dns-static) wave path
    nodes = zoned_nodes([2, 2])
    anchors = [make_pod("anchor-0", labels={"app": "anchor"}, node_name="z0-n0"),
               make_pod("anchor-1", labels={"app": "anchor"}, node_name="z1-n0")]
    pods = replicas("obs", 20, cpu="100m", memory="128Mi")
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = spread("anchor", max_skew=3)
    wc, sc, wf, sf = run_both(nodes, [anchors, pods])
    assert wc == sc and wf == sf


def test_spread_wave_two_constraints():
    # zone + hostname constraints together on one group
    nodes = zoned_nodes([3, 2], cpu="8", memory="16Gi")
    pods = spread_replicas("dual", 25, max_skew=2, cpu="100m", memory="128Mi")
    for p in pods:
        p["spec"]["topologySpreadConstraints"] += spread(
            "dual", key="kubernetes.io/hostname", max_skew=2)
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_spread_wave_segments_are_waves():
    # the segmentation routes a self-matching dns group onto the epoch-batched
    # affinity wave (any topology cardinality since the multi-round epochs)
    nodes = zoned_nodes([2, 2])
    sim = Simulator(copy.deepcopy(nodes))
    pods = spread_replicas("seg", 12, cpu="100m", memory="128Mi")
    bt = sim.encode_batch(copy.deepcopy(pods))
    segs = sim._segments(bt, len(pods))
    assert [s[0] for s in segs] == ["affinity"]


# ------------------------------------------------------------------- gpu waves ----
#
# Shared-GPU groups (no pre-assigned gpu-index) are wave-eligible: depletion is
# unit-countable so capacity is closed-form, and the aggregate commit replays
# the per-node allocator exactly (schedule_wave gpu_live). Censuses, failure
# counts, AND the per-device ledgers must match the serial path.

GI = 1 << 30


def wave_gpu_node(name, count=2, total_mem=32 * GI, cpu="64", memory="256Gi"):
    caps = {"alibabacloud.com/gpu-count": str(count),
            "alibabacloud.com/gpu-mem": str(total_mem)}
    return make_node(name, cpu=cpu, memory=memory, extra_resources=caps)


def wave_gpu_replicas(app, n, mem_gi=4, count=1, **kw):
    pods = replicas(app, n, cpu="500m", memory="1Gi", **kw)
    for p in pods:
        p["metadata"]["annotations"] = {
            "alibabacloud.com/gpu-mem": f"{mem_gi}Gi",
            "alibabacloud.com/gpu-count": str(count),
        }
    return pods


def run_both_gpu(nodes, batches):
    """run_both + per-device ledger comparison."""
    return run_both(nodes, batches, extract=lambda sim: [
        tuple(s.dev_used) if s else None for s in sim.gpu_host.states
    ])


def test_gpu_wave_single_gpu_binpack():
    nodes = [wave_gpu_node(f"g{i}", count=4, total_mem=64 * GI) for i in range(6)]
    pods = wave_gpu_replicas("trainer", 50, mem_gi=4)
    wc, sc, wf, sf, wl, sl = run_both_gpu(nodes, [pods])
    assert wc == sc and wf == sf == {}
    assert wl == sl


def test_gpu_wave_exhaustion_and_ledger():
    # 2 devices x 8Gi per node, 3Gi pods: 2 units per device with 2Gi stranded —
    # the floor() unit math and the tightest-fit replay both matter here
    nodes = [wave_gpu_node(f"g{i}", count=2, total_mem=16 * GI, cpu="128",
                           memory="512Gi") for i in range(4)]
    pods = wave_gpu_replicas("tight", 30, mem_gi=3)
    wc, sc, wf, sf, wl, sl = run_both_gpu(nodes, [pods])
    assert wc == sc and wf == sf
    assert wf.get("tight", 0) > 0  # 4 nodes * 4 units = 16 < 30
    assert wl == sl


def test_gpu_wave_multi_gpu_greedy():
    nodes = [wave_gpu_node(f"g{i}", count=4, total_mem=32 * GI) for i in range(3)]
    pods = wave_gpu_replicas("dual", 16, mem_gi=4, count=2)
    wc, sc, wf, sf, wl, sl = run_both_gpu(nodes, [pods])
    assert wc == sc and wf == sf
    assert wl == sl


def test_gpu_wave_mixed_with_plain_pods():
    nodes = [wave_gpu_node(f"g{i}", count=2, total_mem=16 * GI, cpu="8",
                           memory="16Gi") for i in range(5)]
    a = wave_gpu_replicas("gp", 12, mem_gi=2)
    b = replicas("plain", 20, cpu="250m", memory="512Mi")
    wc, sc, wf, sf, wl, sl = run_both_gpu(nodes, [a + b])
    assert wc == sc and wf == sf
    assert wl == sl


def test_gpu_wave_preassigned_index_stays_serial():
    nodes = [wave_gpu_node(f"g{i}") for i in range(3)]
    sim = Simulator(copy.deepcopy(nodes))
    pods = wave_gpu_replicas("pre", 10)
    for p in pods:
        p["metadata"]["annotations"]["alibabacloud.com/gpu-index"] = "1"
    bt = sim.encode_batch(copy.deepcopy(pods))
    segs = sim._segments(bt, len(pods))
    assert [s[0] for s in segs] == ["serial"]


def test_gpu_wave_segments_are_waves():
    nodes = [wave_gpu_node(f"g{i}") for i in range(3)]
    sim = Simulator(copy.deepcopy(nodes))
    pods = wave_gpu_replicas("seg", 10)
    bt = sim.encode_batch(copy.deepcopy(pods))
    segs = sim._segments(bt, len(pods))
    assert [s[0] for s in segs] == ["wave"]
    assert segs[0][5] is True  # gpu_live


def test_wave_host_ports_cap1():
    # a run of identical host-port pods is a capacity-1-per-node wave: first
    # copy claims the port, placements spread one per node, surplus fails
    nodes = [make_node(f"hp{i}") for i in range(6)]
    pods = replicas("hp", 9, cpu="100m", memory="128Mi", host_ports=[8080])
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    assert sum(wc.values()) == 6 and wf == {"hp": 3}


def test_wave_host_ports_block_later_groups():
    # the wave's aggregate commit must write the port bits: a later group
    # wanting the same port only fits nodes the first group left free
    def app_census(sim):
        out = {}
        for pods in sim.pods_on_node:
            for p in pods:
                app = labels_of(p).get("app")
                out[app] = out.get(app, 0) + 1
        return out

    # first group length >= WAVE_MIN so it truly runs as a WAVE segment: this
    # is the test that the wave's aggregate commit writes the port bits the
    # second group's filter then reads
    nodes = [make_node(f"hpx{i}") for i in range(12)]
    first = replicas("first", 8, cpu="100m", memory="128Mi", host_ports=[9090])
    second = replicas("second", 8, cpu="100m", memory="128Mi", host_ports=[9090])
    wc, sc, wf, sf, wapps, sapps = run_both(nodes, [first + second],
                                            extract=app_census)
    assert wc == sc and wf == sf
    assert wapps == sapps == {"first": 8, "second": 4}
    assert wf == {"second": 4}


def test_wave_host_ports_disabled_filter_unbounded(tmp_path):
    # with the NodePorts plugin disabled, host ports are inert: no cap1, no
    # conflicts — every pod schedules (and waves must agree with serial)
    import yaml

    from open_simulator_tpu.api.schedconfig import parse_scheduler_config

    cfg_path = tmp_path / "sched.yaml"
    cfg_path.write_text(yaml.safe_dump({
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"plugins": {"filter": {"disabled": [{"name": "NodePorts"}]}}}],
    }))
    cfg = parse_scheduler_config(str(cfg_path))
    nodes = [make_node(f"hpd{i}") for i in range(3)]
    pods = replicas("hpd", 9, cpu="100m", memory="128Mi", host_ports=[7070])
    results = []
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes), sched_config=cfg)
        sim.use_waves = waves
        failed = sim.schedule_pods(copy.deepcopy(pods))
        results.append((census_of(sim), len(failed)))
    assert results[0] == results[1]
    assert results[0][1] == 0 and sum(results[0][0].values()) == 9


def _service(name, selector, namespace="default"):
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {"selector": dict(selector)}}


def _run_both_with_services(nodes, services, batches):
    wc, sc, wf, sf, wn, sn = run_both(
        nodes, batches, services=services,
        extract=lambda sim: [len(p) for p in sim.pods_on_node])
    return wc, sc, sum(wf.values()), sum(sf.values()), wn, sn


def test_ss_live_service_backed_deployment_waves():
    # a service-backed workload spreads against its own per-node/zone counts
    # (live SelectorSpread) — routed through the fused group-serial kernel,
    # which must match the pure serial scan placement for placement
    nodes = [make_node(f"ss{i}", labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
             for i in range(9)]
    svc = _service("web-svc", {"app": "web"})
    pods = replicas("web", 24, cpu="200m", memory="256Mi",
                    labels={"app": "web"})
    wc, sc, wf, sf, wn, sn = _run_both_with_services(nodes, [svc], [pods])
    assert wc == sc and wf == sf
    assert sum(wn) == 24 and wf == 0
    # SelectorSpread actually spreads: per-node counts stay near-balanced
    assert max(wn) - min(wn) <= 2


def test_ss_live_seeded_counts_respected():
    # pods of the same service already placed (earlier batch) must seed the
    # live per-node counts: the second batch avoids the loaded nodes first
    nodes = [make_node(f"ssb{i}") for i in range(4)]
    svc = _service("api-svc", {"app": "api"})
    first = replicas("api", 4, cpu="100m", memory="128Mi", labels={"app": "api"})
    second = replicas("api", 8, start=4, cpu="100m", memory="128Mi",
                      labels={"app": "api"})
    wc, sc, wf, sf, wn, sn = _run_both_with_services(nodes, [svc], [first, second])
    assert wc == sc and wf == sf
    assert sum(wn) == 12 and max(wn) == 3 and min(wn) == 3


def test_ss_live_zero_weight_rides_plain_wave(tmp_path):
    # SelectorSpread weight 0 via scheduler config makes the term inert: the
    # group becomes plain-wave eligible and must still match serial
    import yaml

    from open_simulator_tpu.api.schedconfig import parse_scheduler_config
    from open_simulator_tpu.core.types import ResourceTypes

    cfg_path = tmp_path / "sched.yaml"
    cfg_path.write_text(yaml.safe_dump({
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"plugins": {"score": {"disabled": [{"name": "SelectorSpread"}]}}}],
    }))
    cfg = parse_scheduler_config(str(cfg_path))
    nodes = [make_node(f"ssz{i}") for i in range(4)]
    svc = _service("z-svc", {"app": "z"})
    pods = replicas("z", 12, cpu="100m", memory="128Mi", labels={"app": "z"})
    results = []
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes), sched_config=cfg)
        sim.use_waves = waves
        sim.register_cluster_objects(ResourceTypes(services=[copy.deepcopy(svc)]))
        failed = sim.schedule_pods(copy.deepcopy(pods))
        results.append((census_of(sim), len(failed)))
        if waves:
            # eligibility: plain wave (not the spread kernel), ss_live False
            segs = {s[0] for s in sim._segments(sim._last_tables, 12)}
            assert segs == {"wave"}
    assert results[0] == results[1]


def test_ss_live_with_self_anti_affinity_cap1():
    # service + hostname self-anti-affinity: live SelectorSpread AND cap1
    nodes = [make_node(f"ssa{i}") for i in range(6)]
    svc = _service("a-svc", {"app": "a"})
    pods = replicas("a", 9, cpu="100m", memory="128Mi",
                    labels={"app": "a"}, affinity=anti_affinity("a"))
    wc, sc, wf, sf, wn, sn = _run_both_with_services(nodes, [svc], [pods])
    assert wc == sc and wf == sf
    assert sum(wn) == 6 and wf == 3 and max(wn) == 1


def test_spread_epoch_wave_hostname_topology():
    # hostname-level self spread (one domain per node) routes through the
    # epoch-batched spread wave (>=64 domains) and must match serial exactly
    nodes = [make_node(f"ep{i}", pods="4") for i in range(80)]
    pods = replicas("ep", 200, cpu="50m", memory="64Mi", labels={"app": "ep"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 2, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "ep"}},
        }]
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    # skew bound actually held: per-node counts within maxSkew of each other
    per_node = {}
    for (n, _), c in wc.items():
        per_node[n] = per_node.get(n, 0) + c
    assert max(per_node.values()) - min(per_node.get(i, 0) for i in range(80)) <= 2


def test_spread_epoch_wave_hostname_maxskew1_tight():
    # maxSkew=1 hostname spread at overflow: the strictest budget shape
    nodes = [make_node(f"et{i}") for i in range(70)]
    pods = replicas("et", 100, cpu="50m", memory="64Mi", labels={"app": "et"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "et"}},
        }]
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def _sa_constraint(app, max_skew=1, topo="topology.kubernetes.io/zone"):
    return {"maxSkew": max_skew, "topologyKey": topo,
            "whenUnsatisfiable": "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": app}}}


def test_sa_live_soft_spread_waves():
    # ScheduleAnyway soft spread: score-only, counters move with placements —
    # routed through the fused kernel, must match the pure serial scan
    nodes = [make_node(f"sa{i}", labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
             for i in range(9)]
    pods = replicas("soft", 21, cpu="300m", memory="256Mi", labels={"app": "soft"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [_sa_constraint("soft")]
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_sa_live_nodes_missing_topology_key():
    # nodes without the topology key are score-ignored (pts=0) but remain
    # schedulable — the sentinel-masked counter update must keep parity
    nodes = [make_node(f"sam{i}", labels={"topology.kubernetes.io/zone": f"z{i % 2}"})
             for i in range(4)]
    nodes += [make_node(f"sam-nokey{i}") for i in range(2)]
    pods = replicas("softm", 18, cpu="500m", memory="512Mi", labels={"app": "softm"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [_sa_constraint("softm", max_skew=2)]
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_sa_live_mixed_with_dns_constraint():
    # one soft + one hard constraint on the same pods: dns filter state and
    # sa score state both live in the fused kernel
    nodes = [make_node(f"sad{i}", labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
             for i in range(6)]
    pods = replicas("mix", 15, cpu="300m", memory="256Mi", labels={"app": "mix"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [
            _sa_constraint("mix", max_skew=2),
            {"maxSkew": 3, "topologyKey": "topology.kubernetes.io/zone",
             "whenUnsatisfiable": "DoNotSchedule",
             "labelSelector": {"matchLabels": {"app": "mix"}}},
        ]
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_wave_host_ports_cap1_survives_fit_disabled(tmp_path):
    # NodeResourcesFit disabled + NodePorts enabled: capacity is unbounded but
    # the port clamp must survive — waves may not stack same-port copies
    import yaml

    from open_simulator_tpu.api.schedconfig import parse_scheduler_config

    cfg_path = tmp_path / "sched.yaml"
    cfg_path.write_text(yaml.safe_dump({
        "apiVersion": "kubescheduler.config.k8s.io/v1beta1",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [{"plugins": {
            "filter": {"disabled": [{"name": "NodeResourcesFit"}]}}}],
    }))
    cfg = parse_scheduler_config(str(cfg_path))
    nodes = [make_node(f"hpf{i}") for i in range(6)]
    pods = replicas("hpf", 9, cpu="100m", memory="128Mi", host_ports=[8081])
    results = []
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes), sched_config=cfg)
        sim.use_waves = waves
        failed = sim.schedule_pods(copy.deepcopy(pods))
        results.append((census_of(sim), len(failed)))
    assert results[0] == results[1]
    assert results[0][1] == 3 and sum(results[0][0].values()) == 6


@pytest.mark.parametrize("seed", [7, 23, 101, 555, 1234, 9999])
def test_wave_fuzz_mixed_workloads(seed):
    """Randomized waves-vs-serial sweep: random node shapes (zones, taints,
    GPU annotations, tight capacities) and random workload blocks cycling
    plain / tolerating / self-anti-affinity / zone-spread / shared-GPU /
    host-port pods, scheduled across two batches. Census + failure equality
    must hold for every seed — this is the guard that the wave eligibility
    split, the adaptive block depth, and the hidden-continuation logic stay
    exact under shapes no hand-written case anticipated."""
    import random

    rng = random.Random(seed)
    n_nodes = rng.randint(6, 14)
    n_zones = rng.choice([0, 2, 3])
    nodes = []
    for i in range(n_nodes):
        labels = {}
        if n_zones:
            labels["topology.kubernetes.io/zone"] = f"z{i % n_zones}"
        taints = (
            [{"key": "dedicated", "value": "batch", "effect": "NoSchedule"}]
            if rng.random() < 0.25 else None
        )
        annotations = None
        if rng.random() < 0.4:
            annotations = {}
        node = make_node(
            f"fz{i}",
            cpu=f"{rng.randint(2000, 9000)}m",
            memory=str(rng.randint(4, 12) << 30),
            pods=str(rng.randint(8, 40)),
            labels=labels,
            taints=taints,
            annotations=annotations,
        )
        if rng.random() < 0.35:  # GPU node (gpushare extended resource)
            for sect in ("capacity", "allocatable"):
                node["status"][sect]["alibabacloud.com/gpu-count"] = "2"
                node["status"][sect]["alibabacloud.com/gpu-mem"] = str(2 * 8 << 30)
        nodes.append(node)

    def block(bi, kind, n):
        app = f"fz-app{bi}"
        # one constraint flavor per block, so replicas stay one group (runs
        # >= WAVE_MIN actually reach the batched kernels)
        when = rng.choice(["DoNotSchedule", "ScheduleAnyway"]) if kind == 3 else None
        skew = rng.choice([1, 2])
        pods = []
        for i in range(n):
            kw = dict(labels={"app": app},
                      cpu=f"{rng.randint(50, 800)}m",
                      memory=str(rng.randint(64, 1024) << 20))
            if kind == 1:
                kw["tolerations"] = [{"key": "dedicated", "operator": "Exists",
                                      "effect": "NoSchedule"}]
            elif kind == 2:
                kw["affinity"] = anti_affinity(app)
            elif kind == 3 and n_zones:
                pass  # spread added below
            elif kind == 4:
                kw["annotations"] = {"alibabacloud.com/gpu-mem": str(4 << 30),
                                     "alibabacloud.com/gpu-count": "1"}
            elif kind == 5:
                kw["host_ports"] = [30000 + bi]
            p = make_pod(f"{app}-{i}", **kw)
            if kind == 3 and n_zones:
                p["spec"]["topologySpreadConstraints"] = [{
                    "maxSkew": skew,
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": when,
                    "labelSelector": {"matchLabels": {"app": app}},
                }]
            pods.append(p)
        return pods

    all_pods = []
    services = []
    for bi in range(rng.randint(4, 8)):
        kind = rng.randint(0, 5)
        all_pods.extend(block(bi, kind, rng.randint(2, 30)))
        # ~1/3 of blocks are service-backed → live SelectorSpread coverage
        if kind != 3 and rng.random() < 0.35:
            services.append(_service(f"svc-{bi}", {"app": f"fz-app{bi}"}))
    cut = rng.randint(0, len(all_pods))
    wc, sc, wf, sf = run_both(nodes, [all_pods[:cut], all_pods[cut:]],
                              services=services)
    assert wc == sc
    assert wf == sf


def test_wave_f32_ulp_stress():
    # odd capacities and request sizes drive cumulative f32 rounding close to
    # ULP boundaries; the wave score table multiplies (j * req) where serial
    # accumulates one pod at a time, so census equality here guards the eps
    # slack in the NodeResourcesFit bound (ADVICE r2: ULP stress)
    nodes = [make_node(f"odd{i}", cpu=f"{3001 + 7 * i}m",
                       memory=str((7 << 30) + 4097 * i)) for i in range(9)]
    pods = replicas("ulp", 260, cpu="77m", memory=str((333 << 20) + 13))
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_spread_epoch_wave_preloaded_nodes_budget_checked():
    """Regression (code review repro): 64 of 67 identical nodes pre-loaded via
    bound pods, 14 hostname maxSkew=1 spread pods. The skipping epoch must
    never take sorted-tail entries whose budgets were not evaluated — the bug
    stacked 3/3/4 pods on the empty nodes where serial placed 1 per node."""
    nodes = [make_node(f"pre{i}", cpu="4") for i in range(67)]
    preload = []
    for i in range(64):
        preload.append(make_pod(f"seed-{i}", cpu="1", memory="128Mi",
                                node_name=f"pre{i}"))
    pods = replicas("tight", 14, cpu="100m", memory="64Mi",
                    labels={"app": "tight"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "tight"}},
        }]
    wc, sc, wf, sf = run_both(nodes, [preload + pods])
    assert wc == sc and wf == sf
    # maxSkew=1 must hold: no (node, signature) census bucket exceeds 1 pod —
    # seeds are bound one per node and spread pods may not stack either
    assert all(c <= 1 for c in wc.values())


def test_spread_wave_threshold_env_knob(monkeypatch):
    """OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS is the break-even fallback:
    live-DNS groups below the threshold reroute onto the fused group-serial
    scan — placements must not change (routing is purely a performance
    choice), and malformed values fall back silently to the default (0 =
    the affinity wave always runs)."""
    nodes = [make_node(f"kn{i}", labels={"topology.kubernetes.io/zone": f"z{i % 3}"})
             for i in range(9)]
    pods = replicas("kn", 18, cpu="200m", memory="256Mi", labels={"app": "kn"})
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 2, "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "kn"}},
        }]

    def run(env):
        if env is not None:
            monkeypatch.setenv("OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS", env)
        else:
            monkeypatch.delenv("OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS",
                               raising=False)
        sim = Simulator(copy.deepcopy(nodes))
        failed = sim.schedule_pods(copy.deepcopy(pods))
        return census_of(sim), len(failed), sim._wave_eligibility(0).kind

    default_c, default_f, default_route = run(None)
    assert default_route == "affinity"  # default 0: the wave always runs
    high_c, high_f, high_route = run("64")
    assert high_route == "spread"       # 3 zones < 64: fused scan fallback
    assert (high_c, high_f) == (default_c, default_f)  # placements identical
    bad_c, bad_f, bad_route = run("not-a-number")
    assert bad_route == "affinity"      # malformed → default threshold
    assert (bad_c, bad_f) == (default_c, default_f)
