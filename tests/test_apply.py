"""Apply layer: config CR, fake nodes, capacity planning loop, resource guard."""

import os

import pytest

from open_simulator_tpu.api.v1alpha1 import (
    ConfigError,
    parse_simon_config,
    validate_config,
)
from open_simulator_tpu.apply.applier import (
    Applier,
    Options,
    satisfy_resource_setting,
)
from open_simulator_tpu.core.types import NodeStatus
from open_simulator_tpu.models.fakenode import new_fake_nodes

from fixtures import make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "examples", "simon-smoke-config.yaml")
DEMO1_CONFIG = os.path.join(REPO, "examples", "simon-config.yaml")


def test_parse_simon_config():
    cfg = parse_simon_config(CONFIG)
    assert cfg.api_version == "simon/v1alpha1"
    assert cfg.kind == "Config"
    assert cfg.spec.cluster.custom_cluster == "examples/smoke/cluster"
    assert [a.name for a in cfg.spec.app_list] == ["simple"]
    assert cfg.spec.new_node == "examples/smoke/newnode"


def test_validate_config_xor(tmp_path):
    cfg = parse_simon_config(CONFIG)
    os.chdir(REPO)
    validate_config(cfg)  # ok
    cfg.spec.cluster.kube_config = "/nonexistent/kubeconfig"
    with pytest.raises(ConfigError):
        validate_config(cfg)  # both set -> XOR violation
    cfg.spec.cluster.custom_cluster = ""
    with pytest.raises(ConfigError):
        validate_config(cfg)  # kube_config path doesn't exist


def test_new_fake_nodes():
    template = make_node("tmpl", cpu="4", memory="8Gi")
    nodes = new_fake_nodes(template, 3, seed=7)
    assert len(nodes) == 3
    names = {n["metadata"]["name"] for n in nodes}
    assert len(names) == 3
    for n in nodes:
        name = n["metadata"]["name"]
        assert name.startswith("simon-")
        assert n["metadata"]["labels"]["kubernetes.io/hostname"] == name
        assert "simon/new-node" in n["metadata"]["labels"]
    # template itself is never mutated
    assert template["metadata"]["name"] == "tmpl"


def test_new_fake_nodes_none_template():
    assert new_fake_nodes(None, 0) == []
    with pytest.raises(ValueError):
        new_fake_nodes(None, 2)


def test_satisfy_resource_setting_env(monkeypatch):
    node = make_node("n1", cpu="10", memory="10Gi")
    pods = [make_pod(f"p{i}", cpu="2", memory="2Gi", node_name="n1") for i in range(4)]
    statuses = [NodeStatus(node=node, pods=pods)]
    ok, _ = satisfy_resource_setting(statuses)
    assert ok  # 80% <= default 100%
    monkeypatch.setenv("MaxCPU", "60")
    ok, reason = satisfy_resource_setting(statuses)
    assert not ok and "cpu" in reason
    monkeypatch.setenv("MaxCPU", "80")
    ok, _ = satisfy_resource_setting(statuses)
    assert ok  # rate 80 is not > 80
    monkeypatch.setenv("MaxCPU", "bogus")
    with pytest.raises(ConfigError):
        satisfy_resource_setting(statuses)


def test_applier_auto_capacity_planning(tmp_path):
    """6 pods of 2cpu/4Gi on 2×(8cpu/16Gi) nodes: 12cpu needed, 16 available — but
    the app asks 24Gi while 32Gi exist, fits; then force overflow via MaxCPU."""
    os.chdir(REPO)
    out = tmp_path / "report.txt"
    applier = Applier(Options(simon_config=CONFIG, output_file=str(out)))
    result = applier.run()
    assert result is not None
    assert not result.unscheduled_pods
    placed = sum(len(ns.pods) for ns in result.node_status)
    assert placed == 6
    report = out.read_text()
    assert "Node Info" in report and "App Info" in report
    assert "demo-node-1" in report
    # a reused Applier must reopen the output file, not write to a closed one
    result2 = applier.run()
    assert result2 is not None and not result2.unscheduled_pods
    assert "Node Info" in out.read_text()


def test_applier_adds_nodes_when_needed(tmp_path, monkeypatch):
    """With MaxCPU=40 the base cluster (75% cpu) violates the envelope: the planner
    must add fake nodes until average utilization fits."""
    os.chdir(REPO)
    monkeypatch.setenv("MaxCPU", "40")
    out = tmp_path / "report.txt"
    applier = Applier(Options(simon_config=CONFIG, output_file=str(out)))
    result = applier.run()
    assert result is not None
    assert not result.unscheduled_pods
    added = [
        ns for ns in result.node_status
        if "simon/new-node" in (ns.node["metadata"].get("labels") or {})
    ]
    assert added, "expected fake nodes to be added"
    # envelope satisfied at the end
    ok, _ = satisfy_resource_setting(result.node_status)
    assert ok
    assert "added" in out.read_text()


# ------------------------------------------------------------ CapacityPlanner ----


def _planner_fixture(n_base=2, n_pods=20, cpu="2", memory="2Gi"):
    from open_simulator_tpu.apply.applier import CapacityPlanner

    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(n_base)]
    template = make_node("tpl", cpu="8", memory="16Gi")
    pods = [make_pod(f"p-{i}", cpu=cpu, memory=memory) for i in range(n_pods)]
    return CapacityPlanner(base, template, pods), base, template, pods


def test_planner_lower_bound_arithmetic(monkeypatch):
    """20 pods x 2cpu = 40 cpu; base 2x8=16 -> fit needs ceil(24/8)=3 new nodes.
    With MaxCPU=50 the envelope needs int(40000/cpu_a*100) <= 50 -> cpu_a > 78431m
    -> 8 new nodes (16+64=80 cores)."""
    planner, *_ = _planner_fixture()
    monkeypatch.delenv("MaxCPU", raising=False)
    assert planner.lower_bound() == 3
    monkeypatch.setenv("MaxCPU", "50")
    assert planner.lower_bound() == 8


def test_planner_search_minimal_and_probe_agrees(monkeypatch):
    monkeypatch.delenv("MaxCPU", raising=False)
    monkeypatch.delenv("MaxMemory", raising=False)
    planner, base, template, pods = _planner_fixture()
    found, n, hist = planner.search()
    assert found
    # the answer is minimal: n schedules everything, n-1 does not
    ok_n, _ = planner.probe(n)
    assert ok_n
    if n > 0:
        ok_prev, _ = planner.probe(n - 1)
        assert not ok_prev
    # and matches a full simulation at n
    from open_simulator_tpu.models.fakenode import new_fake_nodes
    from open_simulator_tpu.simulator.engine import Simulator

    import copy
    sim = Simulator(base + new_fake_nodes(template, n))
    failed = sim.schedule_pods(copy.deepcopy(pods))
    assert not failed


def test_planner_probe_does_not_mutate_pods(monkeypatch):
    monkeypatch.delenv("MaxCPU", raising=False)
    planner, _, _, pods = _planner_fixture()
    planner.probe(4)
    for p in pods:
        assert "nodeName" not in p["spec"]
        assert p.get("status") is None


def test_planner_skips_daemonsets():
    from open_simulator_tpu.apply.applier import CapacityPlanner
    from open_simulator_tpu.core.types import AppResource, ResourceTypes

    cluster = ResourceTypes()
    cluster.nodes = [make_node("n0")]
    app = ResourceTypes()
    app.daemon_sets = [{"kind": "DaemonSet", "metadata": {"name": "ds"}}]
    tpl = make_node("tpl")
    assert CapacityPlanner.try_build(
        cluster, [AppResource(name="a", resource=app)], tpl, []) is None
    # without the DS it builds
    app2 = ResourceTypes()
    assert CapacityPlanner.try_build(
        cluster, [AppResource(name="a", resource=app2)], tpl, []) is not None


def test_planner_path_matches_full_search(tmp_path, monkeypatch):
    """The applier's planner fast path and the full-simulation search must agree
    on the node count and scheduled placements for the demo config."""
    os.chdir(REPO)
    monkeypatch.setenv("MaxCPU", "40")
    import open_simulator_tpu.apply.applier as A

    out1 = tmp_path / "fast.txt"
    ap1 = Applier(Options(simon_config=CONFIG, output_file=str(out1)))
    res1 = ap1.run()

    monkeypatch.setattr(A.CapacityPlanner, "try_build",
                        classmethod(lambda cls, *a, **k: None))
    out2 = tmp_path / "slow.txt"
    ap2 = Applier(Options(simon_config=CONFIG, output_file=str(out2)))
    res2 = ap2.run()
    assert (res1 is None) == (res2 is None)
    if res1 is not None:
        n1 = sum(1 for ns in res1.node_status
                 if "simon/new-node" in (ns.node["metadata"].get("labels") or {}))
        n2 = sum(1 for ns in res2.node_status
                 if "simon/new-node" in (ns.node["metadata"].get("labels") or {}))
        # the planner returns the exact minimum; the doubling search may only
        # ever return MORE nodes than necessary
        assert n1 <= n2
        placed1 = sum(len(ns.pods) for ns in res1.node_status)
        placed2 = sum(len(ns.pods) for ns in res2.node_status)
        assert placed1 == placed2


def test_planner_homeless_pods_not_failures(monkeypatch):
    """Pods bound to unknown nodes are dropped from every report by the engine;
    probes and the lower bound must not count them as failures or load."""
    from open_simulator_tpu.apply.applier import CapacityPlanner

    monkeypatch.delenv("MaxCPU", raising=False)
    base = [make_node("base-0", cpu="8", memory="16Gi")]
    template = make_node("tpl", cpu="8", memory="16Gi")
    pods = [make_pod("ghost", cpu="64", memory="64Gi", node_name="no-such-node")]
    pods += [make_pod(f"p-{i}", cpu="1", memory="1Gi") for i in range(4)]
    planner = CapacityPlanner(base, template, pods)
    assert planner.lower_bound() == 0  # the ghost's 64 cpu must not count
    ok, nf = planner.probe(0)
    assert ok and nf == 0


def test_planner_rejects_bound_after_unbound():
    from open_simulator_tpu.apply.applier import CapacityPlanner
    from open_simulator_tpu.core.types import AppResource, ResourceTypes

    cluster = ResourceTypes()
    cluster.nodes = [make_node("n0")]
    cluster.pods = [make_pod("pending-first"),
                    make_pod("bound-later", node_name="n0")]
    tpl = make_node("tpl")
    assert CapacityPlanner.try_build(cluster, [], tpl, []) is None
    # bound-then-pending order is the equivalent one and builds
    cluster2 = ResourceTypes()
    cluster2.nodes = [make_node("n0")]
    cluster2.pods = [make_pod("bound-first", node_name="n0"),
                     make_pod("pending-later")]
    assert CapacityPlanner.try_build(cluster2, [], tpl, []) is not None
