"""Apply layer: config CR, fake nodes, capacity planning loop, resource guard."""

import os

import pytest

from open_simulator_tpu.api.v1alpha1 import (
    ConfigError,
    parse_simon_config,
    validate_config,
)
from open_simulator_tpu.apply.applier import (
    Applier,
    Options,
    satisfy_resource_setting,
)
from open_simulator_tpu.core.types import NodeStatus
from open_simulator_tpu.models.fakenode import new_fake_nodes

from fixtures import make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "examples", "simon-smoke-config.yaml")
DEMO1_CONFIG = os.path.join(REPO, "examples", "simon-config.yaml")


def test_parse_simon_config():
    cfg = parse_simon_config(CONFIG)
    assert cfg.api_version == "simon/v1alpha1"
    assert cfg.kind == "Config"
    assert cfg.spec.cluster.custom_cluster == "examples/smoke/cluster"
    assert [a.name for a in cfg.spec.app_list] == ["simple"]
    assert cfg.spec.new_node == "examples/smoke/newnode"


def test_validate_config_xor(tmp_path):
    cfg = parse_simon_config(CONFIG)
    os.chdir(REPO)
    validate_config(cfg)  # ok
    cfg.spec.cluster.kube_config = "/nonexistent/kubeconfig"
    with pytest.raises(ConfigError):
        validate_config(cfg)  # both set -> XOR violation
    cfg.spec.cluster.custom_cluster = ""
    with pytest.raises(ConfigError):
        validate_config(cfg)  # kube_config path doesn't exist


def test_new_fake_nodes():
    template = make_node("tmpl", cpu="4", memory="8Gi")
    nodes = new_fake_nodes(template, 3, seed=7)
    assert len(nodes) == 3
    names = {n["metadata"]["name"] for n in nodes}
    assert len(names) == 3
    for n in nodes:
        name = n["metadata"]["name"]
        assert name.startswith("simon-")
        assert n["metadata"]["labels"]["kubernetes.io/hostname"] == name
        assert "simon/new-node" in n["metadata"]["labels"]
    # template itself is never mutated
    assert template["metadata"]["name"] == "tmpl"


def test_new_fake_nodes_none_template():
    assert new_fake_nodes(None, 0) == []
    with pytest.raises(ValueError):
        new_fake_nodes(None, 2)


def test_satisfy_resource_setting_env(monkeypatch):
    node = make_node("n1", cpu="10", memory="10Gi")
    pods = [make_pod(f"p{i}", cpu="2", memory="2Gi", node_name="n1") for i in range(4)]
    statuses = [NodeStatus(node=node, pods=pods)]
    ok, _ = satisfy_resource_setting(statuses)
    assert ok  # 80% <= default 100%
    monkeypatch.setenv("MaxCPU", "60")
    ok, reason = satisfy_resource_setting(statuses)
    assert not ok and "cpu" in reason
    monkeypatch.setenv("MaxCPU", "80")
    ok, _ = satisfy_resource_setting(statuses)
    assert ok  # rate 80 is not > 80
    monkeypatch.setenv("MaxCPU", "bogus")
    with pytest.raises(ConfigError):
        satisfy_resource_setting(statuses)


def test_applier_auto_capacity_planning(tmp_path):
    """6 pods of 2cpu/4Gi on 2×(8cpu/16Gi) nodes: 12cpu needed, 16 available — but
    the app asks 24Gi while 32Gi exist, fits; then force overflow via MaxCPU."""
    os.chdir(REPO)
    out = tmp_path / "report.txt"
    applier = Applier(Options(simon_config=CONFIG, output_file=str(out)))
    result = applier.run()
    assert result is not None
    assert not result.unscheduled_pods
    placed = sum(len(ns.pods) for ns in result.node_status)
    assert placed == 6
    report = out.read_text()
    assert "Node Info" in report and "App Info" in report
    assert "demo-node-1" in report
    # a reused Applier must reopen the output file, not write to a closed one
    result2 = applier.run()
    assert result2 is not None and not result2.unscheduled_pods
    assert "Node Info" in out.read_text()


def test_applier_adds_nodes_when_needed(tmp_path, monkeypatch):
    """With MaxCPU=40 the base cluster (75% cpu) violates the envelope: the planner
    must add fake nodes until average utilization fits."""
    os.chdir(REPO)
    monkeypatch.setenv("MaxCPU", "40")
    out = tmp_path / "report.txt"
    applier = Applier(Options(simon_config=CONFIG, output_file=str(out)))
    result = applier.run()
    assert result is not None
    assert not result.unscheduled_pods
    added = [
        ns for ns in result.node_status
        if "simon/new-node" in (ns.node["metadata"].get("labels") or {})
    ]
    assert added, "expected fake nodes to be added"
    # envelope satisfied at the end
    ok, _ = satisfy_resource_setting(result.node_status)
    assert ok
    assert "added" in out.read_text()
