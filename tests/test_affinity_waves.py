"""Affinity wave == serial scan, placement for placement.

schedule_affinity_wave (ops/kernels.py) extends the epoch-batched wave
machinery to counter-live hard predicates: required InterPodAffinity (incl.
the bootstrap special case), required anti-affinity in both directions,
low-cardinality (zone-level) DoNotSchedule spread, and live SelectorSpread.
Every test here runs the same pod sequence through a waves-on and a waves-off
Simulator and compares the per-(node, signature) placement census — the same
bit-identity contract tests/test_waves.py holds the plain and spread waves to.
"""

import copy

from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod
from test_waves import census_of, replicas, run_both


ZONE = "topology.kubernetes.io/zone"


def zoned(n, n_zones, **kw):
    return [make_node(f"n{i}", labels={ZONE: f"z{i % n_zones}"}, **kw)
            for i in range(n)]


def with_affinity(pods, app, topo, kind="podAffinity"):
    for p in pods:
        aff = p["spec"].setdefault("affinity", {})
        aff[kind] = {"requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchLabels": {"app": app}},
             "topologyKey": topo}]}
    return pods


def with_spread(pods, app, max_skew=1, topo=ZONE):
    for p in pods:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": max_skew, "topologyKey": topo,
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": app}}}]
    return pods


# ------------------------------------------------------------ routing ---------


def test_affinity_segments_route_to_the_wave():
    sim = Simulator(zoned(8, 4, cpu="8"))
    cases = {
        "aff": with_affinity(replicas("aff", 10, cpu="100m", memory="128Mi"),
                             "aff", ZONE),
        "anti": with_affinity(replicas("anti", 10, cpu="100m", memory="128Mi"),
                              "anti", ZONE, "podAntiAffinity"),
        "dns": with_spread(replicas("dns", 10, cpu="100m", memory="128Mi"),
                           "dns"),
    }
    for name, pods in cases.items():
        bt = sim.encode_batch(copy.deepcopy(pods))
        segs = sim._segments(bt, len(pods))
        assert [s[0] for s in segs] == ["affinity"], name


def test_wave_elig_cache_invalidated_on_flag_change():
    """Regression: eligibility is cached per group but reads filter_flags and
    score weights — mutating them on a reused Simulator must re-route, not
    return the stale decision."""
    sim = Simulator(zoned(6, 3, cpu="8"))
    pods = with_spread(replicas("kc", 8, cpu="100m", memory="128Mi"), "kc")
    sim.schedule_pods(copy.deepcopy(pods))
    assert sim._wave_eligibility(0).kind == "affinity"
    # disabling the spread filter makes the term inert → plain wave
    sim.filter_flags = sim.filter_flags._replace(spread=False)
    assert sim._wave_eligibility(0).kind == "wave"
    sim.filter_flags = sim.filter_flags._replace(spread=True)
    assert sim._wave_eligibility(0).kind == "affinity"
    # zeroing the PodTopologySpread score weight flips sa-liveness routing
    # on a soft-spread group the same way (weights are part of the digest)
    sa = replicas("sa", 8, cpu="100m", memory="128Mi")
    for p in sa:
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": ZONE,
            "whenUnsatisfiable": "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": "sa"}}}]
    sim.schedule_pods(copy.deepcopy(sa))
    gi = next(i for i, g in enumerate(sim.encoder.group_list) if g.spread_sa)
    assert sim._wave_eligibility(gi).kind == "spread"
    sim.score_w = sim.score_w._replace(pts=0.0)
    assert sim._wave_eligibility(gi).kind == "wave"


# ------------------------------------------- required affinity (podAffinity) --


def test_required_self_affinity_zone_bootstrap_and_clump():
    # empty cluster: the first pod bootstraps anywhere, the rest must clump
    # into its zone — gate goes live after placement one
    nodes = zoned(12, 4, cpu="8")
    pods = with_affinity(replicas("cl", 30, cpu="100m", memory="128Mi"),
                         "cl", ZONE)
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    zones_used = {i % 4 for (i, _sig) in wc}
    assert len(zones_used) == 1  # the clump stayed in one zone


def test_required_self_affinity_hostname():
    nodes = [make_node(f"h{i}", cpu="4") for i in range(9)]
    pods = with_affinity(replicas("hn", 20, cpu="100m", memory="128Mi"),
                         "hn", "kubernetes.io/hostname")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_required_affinity_seeded_counts_skip_bootstrap():
    # pre-bound matching pods in two zones: no bootstrap, the gate admits
    # exactly those zones from the first wave pod on
    nodes = zoned(12, 4, cpu="8")
    seed = [make_pod("s0", labels={"app": "sd"}, node_name="n0",
                     cpu="100m", memory="128Mi"),
            make_pod("s1", labels={"app": "sd"}, node_name="n1",
                     cpu="100m", memory="128Mi")]
    pods = with_affinity(replicas("sd", 24, cpu="100m", memory="128Mi"),
                         "sd", ZONE)
    wc, sc, wf, sf = run_both(nodes, [seed, pods])
    assert wc == sc and wf == sf
    landed_zones = {i % 4 for (i, _sig) in wc}
    assert landed_zones <= {0, 1}


def test_required_affinity_capacity_pushes_across_nodes():
    # tiny nodes: the clump must spill across its zone's nodes in serial's
    # exact order (normalizer sandwich + per-node capacity)
    nodes = zoned(8, 2, cpu="1", pods="3")
    pods = with_affinity(replicas("sp", 16, cpu="200m", memory="64Mi"),
                         "sp", ZONE)
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


# -------------------------------------------------- anti-affinity directions --


def test_self_anti_affinity_zone_one_per_domain():
    # both directions live (incoming term + carried term) composed into one
    # budget meter: exactly one pod per zone
    nodes = zoned(12, 4, cpu="8")
    pods = with_affinity(replicas("az", 10, cpu="100m", memory="128Mi"),
                         "az", ZONE, "podAntiAffinity")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    assert sum(wc.values()) == 4  # one per zone, six unschedulable


def test_existing_pods_anti_affinity_seeded_blocks_zone():
    # a seeded pod's carried anti term blocks its whole zone for the wave run
    nodes = zoned(12, 4, cpu="8")
    seed = with_affinity([make_pod("s0", labels={"app": "ez"},
                                   node_name="n0", cpu="100m", memory="128Mi")],
                         "ez", ZONE, "podAntiAffinity")
    pods = with_affinity(replicas("ez", 8, cpu="100m", memory="128Mi"),
                         "ez", ZONE, "podAntiAffinity")
    wc, sc, wf, sf = run_both(nodes, [seed, pods])
    assert wc == sc and wf == sf
    assert not any(i % 4 == 0 for (i, _sig) in wc if _sig is not None and i != 0)


def test_anti_affinity_against_other_app_static_gate():
    # anti term tracking a DIFFERENT app stays a static gate (plain wave):
    # routing must not regress it onto slower paths, placements identical
    nodes = zoned(8, 4, cpu="8")
    anchors = [make_pod("an-0", labels={"app": "anchor"}, node_name="n0",
                        cpu="100m", memory="128Mi"),
               make_pod("an-1", labels={"app": "anchor"}, node_name="n1",
                        cpu="100m", memory="128Mi")]
    pods = with_affinity(replicas("obs", 12, cpu="100m", memory="128Mi"),
                         "anchor", ZONE, "podAntiAffinity")
    wc, sc, wf, sf = run_both(nodes, [anchors, pods])
    assert wc == sc and wf == sf
    sim = Simulator(copy.deepcopy(nodes))
    sim.schedule_pods(copy.deepcopy(anchors))
    bt = sim.encode_batch(copy.deepcopy(pods))
    segs = sim._segments(bt, len(pods))
    assert [s[0] for s in segs] == ["wave"]


# ------------------------------------------------------------- zone-level DNS --


def test_zone_spread_low_cardinality_rides_the_wave():
    # the hard-predicate bench shape: few zones, DoNotSchedule, self-matching
    nodes = zoned(15, 5, cpu="4")
    pods = with_spread(replicas("zs", 60, cpu="100m", memory="128Mi"),
                       "zs", max_skew=2)
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    sim = Simulator(copy.deepcopy(nodes))
    bt = sim.encode_batch(copy.deepcopy(pods))
    assert [s[0] for s in sim._segments(bt, len(pods))] == ["affinity"]


def test_zone_spread_skewed_capacity_binds():
    nodes = (zoned(6, 1, cpu="4")
             + [make_node(f"b{i}", labels={ZONE: "z1"}, cpu="4")
                for i in range(3)]
             + [make_node("c0", labels={ZONE: "z2"}, cpu="4")])
    pods = with_spread(replicas("sk", 80, cpu="200m", memory="256Mi"), "sk")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    assert sum(wc.values()) < 80  # the one-node zone caps the run


def test_zone_spread_odd_epoch_sizes():
    # prime-ish node/pod/zone counts + maxSkew 1: exercises mid-round m-cuts
    # and min-rise boundaries on every epoch
    nodes = zoned(13, 5, cpu="2")
    pods = with_spread(replicas("odd", 37, cpu="150m", memory="128Mi"), "odd")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_zone_spread_seeded_blocked_then_min_rise():
    # one zone seeded far above the rest starts blocked and is re-admitted
    # round by round as the min rises — the multi-round budget direction
    nodes = zoned(9, 3, cpu="16")
    seed = with_spread([make_pod(f"seed-{i}", labels={"app": "r"},
                                 node_name="n0", cpu="100m", memory="128Mi")
                        for i in range(5)], "r", max_skew=2)
    pods = with_spread(replicas("r", 40, cpu="100m", memory="128Mi"),
                       "r", max_skew=2)
    wc, sc, wf, sf = run_both(nodes, [seed, pods])
    assert wc == sc and wf == sf


# ------------------------------------------------------------- mixed groups ---


def test_mixed_spread_plus_hostname_self_anti_cap1():
    nodes = zoned(10, 3, cpu="4")
    pods = with_spread(replicas("mx", 25, cpu="200m", memory="256Mi"),
                       "mx", max_skew=2)
    with_affinity(pods, "mx", "kubernetes.io/hostname", "podAntiAffinity")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    assert all(c <= 1 for c in wc.values())  # cap1 held on the wave


def test_mixed_affinity_plus_zone_anti_head_fallback():
    # zone affinity + hostname anti on the same group: the budget terms do
    # not compose, so the wave degrades to exact head-pick epochs
    nodes = zoned(12, 4, cpu="8")
    pods = with_affinity(replicas("mix", 12, cpu="100m", memory="128Mi"),
                         "mix", ZONE)
    with_affinity(pods, "mix", "kubernetes.io/hostname", "podAntiAffinity")
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf


def test_mixed_groups_interleaved_batches():
    # affinity, anti, spread, and plain groups interleaved in one call: the
    # carries seed each segment from the previous ones in serial order
    nodes = zoned(12, 4, cpu="8")
    plain = replicas("pl", 10, cpu="100m", memory="128Mi")
    aff = with_affinity(replicas("af", 10, cpu="100m", memory="128Mi"),
                        "af", ZONE)
    anti = with_affinity(replicas("an", 10, cpu="100m", memory="128Mi"),
                         "an", ZONE, "podAntiAffinity")
    dns = with_spread(replicas("dz", 10, cpu="100m", memory="128Mi"), "dz")
    wc, sc, wf, sf = run_both(nodes, [plain + aff + anti + dns])
    assert wc == sc and wf == sf


def test_probe_pods_counts_affinity_wave_groups():
    # the probe path dispatches the same affinity-wave segments; its counted
    # result must equal the number schedule_pods actually places
    nodes = zoned(12, 4, cpu="8")
    pods = with_affinity(replicas("pr", 10, cpu="100m", memory="128Mi"),
                         "pr", ZONE, "podAntiAffinity")
    probe = Simulator(copy.deepcopy(nodes))
    scheduled, total = probe.probe_pods(copy.deepcopy(pods))
    real = Simulator(copy.deepcopy(nodes))
    failed = real.schedule_pods(copy.deepcopy(pods))
    assert (scheduled, total) == (len(pods) - len(failed), len(pods))
    assert scheduled == 4  # one per zone
    # probing must not materialize placements
    assert sum(len(p) for p in probe.pods_on_node) == 0


def test_probe_affinity_wave_fanout_matches_single_lane():
    # the capacity prober's vmapped fan-out must equal per-lane dispatches:
    # lane 0 = all nodes active, lane 1 = half the nodes masked off
    import numpy as np

    from open_simulator_tpu.ops import kernels

    nodes = zoned(8, 4, cpu="4")
    pods = with_spread(replicas("fo", 12, cpu="200m", memory="256Mi"),
                       "fo", max_skew=2)
    sim = Simulator(copy.deepcopy(nodes))
    bt = sim.encode_batch(copy.deepcopy(pods))
    tables, carry = sim._to_device(bt)
    N = bt.alloc.shape[0]
    active = np.ones((2, N), bool)
    active[1, :] = False
    active[1, :2] = True  # zones 2/3 masked off entirely: skew vs their
    # (encode-time) eligible domains pins the active zones at maxSkew
    block = kernels.wave_block_for(len(pods), sim.na.N)

    import jax.numpy as jnp

    carry_s = type(carry)(*(jnp.stack([leaf, leaf]) for leaf in carry))
    _, placed_s = kernels.probe_affinity_wave_fanout(
        tables, carry_s, jnp.asarray(active), np.int32(0),
        np.int32(len(pods)), np.bool_(False),
        w=sim.score_w, filters=sim.filter_flags, block=block)
    for lane in range(2):
        masked = tables._replace(
            static_mask=tables.static_mask & jnp.asarray(active[lane])[None, :])
        _, _, placed = kernels.schedule_affinity_wave(
            masked, carry, np.int32(0), np.int32(len(pods)), np.bool_(False),
            w=sim.score_w, filters=sim.filter_flags, block=block)
        assert int(placed_s[lane]) == int(placed), lane
    assert int(placed_s[1]) < int(placed_s[0])  # masking half costs capacity


def test_heterogeneous_nodes_norm_sandwich():
    # uneven allocatables/odd byte sizes: normalizer values differ per node,
    # so the sandwich check must actually gate the big takes
    nodes = [make_node(f"hz{i}", labels={ZONE: f"z{i % 3}"},
                       cpu=f"{2001 + 997 * i}m",
                       memory=str((3 << 30) + 7919 * i)) for i in range(9)]
    pods = with_spread(replicas("hz", 40, cpu="77m", memory=str((128 << 20) + 13)),
                       "hz", max_skew=2)
    wc, sc, wf, sf = run_both(nodes, [pods])
    assert wc == sc and wf == sf
    aff = with_affinity(replicas("ha", 30, cpu="99m", memory="96Mi"),
                        "ha", ZONE)
    wc, sc, wf, sf = run_both(nodes, [aff])
    assert wc == sc and wf == sf
