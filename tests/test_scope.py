"""simonscope tests: trace propagation across the hard serving paths
(micro-batch demux, fresh-path detours, failover replays), SLO engine
quantile/burn accounting, the consistent-snapshot metrics fix under a
16-thread hammer, and runtime-sampler lifecycle.

The contract under test (ISSUE 14 acceptance):
- every served request yields ONE complete span tree whose phase spans and
  counters reconcile exactly with the simon_serve_* / simon_scope_* metric
  families;
- a failover replay keeps the request's trace id across both backend
  attempts; a census-dependent request's fresh detour is traced under the
  same id;
- tracing off reproduces bit-identical placements and byte-identical
  metrics (scope families emit no samples);
- a /metrics scrape racing 16 updating threads never renders a torn
  histogram row (one locked snapshot per family per scrape).
"""

import json
import math
import threading
import time

import pytest

from open_simulator_tpu.obs import REGISTRY, Registry
from open_simulator_tpu.obs import scope
from open_simulator_tpu.obs.scope import SLOEngine, _WindowHist
from open_simulator_tpu.resilience import FaultPlan, FaultSpec, installed
from open_simulator_tpu.resilience import guard
from open_simulator_tpu.serve import ResidentImage, WhatIfService

from fixtures import make_node, make_pod


@pytest.fixture(autouse=True)
def _clean_scope_and_guard():
    scope.disable()
    guard.reset_for_tests()
    yield
    scope.disable()
    guard.reset_for_tests()


def _vals():
    return REGISTRY.values()


def make_image(n_nodes=10, n_bound=4):
    nodes = [make_node(f"n-{i}", cpu="8", memory="16Gi")
             for i in range(n_nodes)]
    bound = [make_pod(f"b-{i}", cpu="1", memory="1Gi",
                      node_name=f"n-{i % n_nodes}",
                      labels={"app": f"base-{i % 2}"})
             for i in range(n_bound)]
    img = ResidentImage.try_build(nodes, pods=bound)
    assert img is not None
    return img


def whatif(tag, n=2):
    return [make_pod(f"wi-{tag}-{j}", cpu="1", memory="1Gi",
                     labels={"app": f"wi-{tag}"}) for j in range(n)]


# ------------------------------------------------------------ SLO engine -----


def test_window_hist_quantiles_interpolate():
    h = _WindowHist(window_s=60.0, n_slices=12)
    now = 1000.0
    for ms in (1, 2, 4, 8, 100):
        h.record(ms / 1000.0, now)
    counts, total, n = h.merged(now)
    assert n == 5
    assert abs(total - 0.115) < 1e-9
    p50 = _WindowHist.quantile(counts, n, 0.50)
    assert 0.002 <= p50 <= 0.008
    p99 = _WindowHist.quantile(counts, n, 0.99)
    assert p99 >= 0.064  # the 100ms outlier's bucket


def test_window_hist_slides_old_slices_out():
    h = _WindowHist(window_s=10.0, n_slices=5)
    h.record(0.001, 0.0)
    assert h.merged(1.0)[2] == 1
    # 20s later the window has slid past the old slice entirely
    assert h.merged(20.0)[2] == 0


def test_slo_engine_targets_and_burn():
    eng = SLOEngine(targets={"ep": {"p99_ms": 10.0, "availability": 0.9}})
    for _ in range(8):
        eng.record("ep", "batched", {"total": 0.001})
    for _ in range(2):
        eng.record("ep", "batched", {"total": 0.5})  # violations
    snap = eng.snapshot()["endpoints"]["ep"]
    assert snap["slo"]["requests"] == 10
    assert snap["slo"]["violations"] == 2
    # bad fraction 0.2 over an allowed 0.1 -> burning at 2x
    assert abs(snap["slo"]["budget_burn"] - 2.0) < 1e-6
    assert snap["routes"] == {"batched": 10}


def test_slo_engine_error_counts_as_violation():
    eng = SLOEngine(targets={"ep": {"p99_ms": 1000.0, "availability": 0.5}})
    eng.record("ep", "error", {"total": 0.001}, error=True)
    assert eng.snapshot()["endpoints"]["ep"]["slo"]["violations"] == 1


# -------------------------------------------------- micro-batch demux trace --


def test_micro_batch_demux_complete_span_trees():
    """N concurrent requests -> N complete span trees from one (or few)
    coalesced dispatches, with queue-wait and lane counts reconciling
    exactly with the simon_serve_* counters."""
    img = make_image()
    svc = WhatIfService(img, window_ms=50.0, fanout=8)
    sc = scope.enable()
    v0 = _vals()
    results = [None] * 8

    def run(i):
        results[i] = svc.submit(whatif(f"d{i}"))

    ts = [threading.Thread(target=run, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    svc.stop()
    assert all(r is not None and r["path"] == "batched" for r in results)
    v1 = _vals()
    events = sc.events()
    roots = [e for e in events if e["name"] == "request:whatif"]
    assert len(roots) == 8
    # one complete tree per trace id: queue_wait + batched_dispatch +
    # fetch + reply all carry the root's id
    by_trace = {}
    for e in events:
        t = (e.get("args") or {}).get("trace_id")
        if t is not None:
            by_trace.setdefault(t, []).append(e["name"])
    assert len(by_trace) == 8
    for names in by_trace.values():
        assert {"request:whatif", "queue_wait", "batched_dispatch",
                "fetch", "reply"} <= set(names)
    # flow stitches pair up (one s + one f per request)
    flows = [e for e in events if e.get("cat") == "flow"]
    assert sum(1 for e in flows if e["ph"] == "s") == 8
    assert sum(1 for e in flows if e["ph"] == "f") == 8
    # lane counts reconcile exactly: per-batch lane widths from the trace
    # must sum to the request count AND match the serve histogram delta
    batch_spans = [e for e in events if e["name"] == "serve_batch"]
    d_batches = (v1.get("simon_serve_batches_total", 0)
                 - v0.get("simon_serve_batches_total", 0))
    assert len(batch_spans) == d_batches
    assert sum(e["args"]["lanes"] for e in batch_spans) == 8
    d_lanes_sum = (v1.get("simon_serve_batch_lanes_sum", 0)
                   - v0.get("simon_serve_batch_lanes_sum", 0))
    assert d_lanes_sum == 8
    d_req = (v1.get('simon_scope_requests_total{endpoint="whatif",'
                    'route="batched"}', 0)
             - v0.get('simon_scope_requests_total{endpoint="whatif",'
                      'route="batched"}', 0))
    assert d_req == 8
    # trace totals == SLO histogram sum (same floats)
    span_total = math.fsum(e["args"]["total_s"] for e in roots)
    d_sum = (v1.get('simon_scope_request_phase_seconds_sum'
                    '{endpoint="whatif",phase="total"}', 0.0)
             - v0.get('simon_scope_request_phase_seconds_sum'
                      '{endpoint="whatif",phase="total"}', 0.0))
    assert abs(span_total - d_sum) <= 1e-9


def test_kernel_spans_ride_the_watchdog_worker_thread():
    """The dispatch/fetch spans are emitted from inside guard.supervised's
    worker (contextvars carry the sink + ctx): the trace shows them on a
    tid different from the submitting thread."""
    img = make_image()
    svc = WhatIfService(img, window_ms=1.0, fanout=4)
    sc = scope.enable()
    svc.submit(whatif("k"))
    svc.stop()
    kernel_spans = [e for e in sc.events()
                    if e["name"].startswith("kernel:serve_")]
    assert kernel_spans, "kernel dispatch produced no span"
    assert all(e["tid"] != threading.get_ident() for e in kernel_spans)


# ------------------------------------------------------- fresh-path detour ---


def test_fresh_detour_traced_under_same_trace_id():
    """A census-dependent request (topology spread) routes to the fresh
    path; the detour is traced under the request's own trace id and the SLO
    route mix records it as fresh."""
    img = make_image()
    svc = WhatIfService(img, window_ms=1.0, fanout=4)
    sc = scope.enable()
    pod = make_pod("spread-1", cpu="1", memory="1Gi",
                   labels={"app": "spread"})
    pod["spec"]["topologySpreadConstraints"] = [{
        "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "spread"}},
    }]
    r = svc.submit([pod])
    svc.stop()
    assert r["path"] == "fresh"
    events = sc.events()
    roots = [e for e in events if e["name"] == "request:whatif"]
    assert len(roots) == 1
    root = roots[0]
    assert root["args"]["route"] == "fresh"
    assert root["args"]["attempts"] == ["fresh"]
    tid_ = root["args"]["trace_id"]
    detours = [e for e in events if e["name"] == "fresh_detour"]
    assert len(detours) == 1
    assert detours[0]["args"]["trace_id"] == tid_
    assert "spread" in detours[0]["args"]["gate"]
    # the engine's probe span nests under the same trace (ctx carried into
    # the fresh Simulator call on the submitting thread)
    probes = [e for e in events if e["name"] == "engine.probe_pods"
              and (e.get("args") or {}).get("trace_id") == tid_]
    assert probes, "fresh detour did not trace the engine probe"
    snap = sc.slo.snapshot()["endpoints"]["whatif"]
    assert snap["routes"] == {"fresh": 1}


# -------------------------------------------------------- failover replay ----


def test_failover_replay_keeps_one_trace_id():
    """An injected watchdog_wedge mid-serve fails the batch over to
    per-request fresh replays: ONE trace id covers the batched attempt and
    its replacement, attempts = [batched, fresh_replay], and the guard
    failover counter moves."""
    img = make_image()
    svc = WhatIfService(img, window_ms=1.0, fanout=4)
    sc = scope.enable()
    v0 = _vals()
    with installed(FaultPlan([FaultSpec("watchdog_wedge", 1)])):
        r = svc.submit(whatif("wedge"))
    svc.stop()
    assert r["path"] == "fresh"
    v1 = _vals()
    assert (v1.get('simon_guard_failovers_total{cause="watchdog_wedge"}', 0)
            > v0.get('simon_guard_failovers_total{cause="watchdog_wedge"}', 0))
    events = sc.events()
    roots = [e for e in events if e["name"] == "request:whatif"]
    assert len(roots) == 1
    root = roots[0]
    assert root["args"]["attempts"] == ["batched", "fresh_replay"]
    tid_ = root["args"]["trace_id"]
    replays = [e for e in events if e["name"] == "fresh_replay"]
    assert len(replays) == 1
    assert replays[0]["args"]["trace_id"] == tid_
    assert replays[0]["args"]["cause"] == "watchdog_wedge"
    # every span of this request carries the SAME trace id — the probe ran
    # on the dispatcher thread under use_ctx, not a fresh trace
    ids = {(e.get("args") or {}).get("trace_id")
           for e in events if (e.get("args") or {}).get("trace_id")}
    assert ids == {tid_}


# --------------------------------------------------------- off bit-identity --


def test_scope_off_bit_identity_and_silent_metrics():
    img = make_image()
    svc = WhatIfService(img, window_ms=1.0, fanout=4)
    reqs = [whatif(f"bi{i}") for i in range(4)]
    v0 = _vals()
    off = [svc.submit(r) for r in reqs]
    v1 = _vals()
    # scope-off serving moved NO simon_scope_* sample (byte-identity of the
    # scope families; other tests in this process may have touched them)
    moved = {k for k in set(v0) | set(v1)
             if k.startswith("simon_scope_")
             and v0.get(k, 0) != v1.get(k, 0)}
    assert not moved, moved
    sc = scope.enable()
    on = [svc.submit(r) for r in reqs]
    svc.stop()
    assert on == off
    assert len([e for e in sc.events()
                if e["name"] == "request:whatif"]) == 4


# ------------------------------------------- consistent-snapshot hammer fix --


def test_metrics_render_consistent_under_16_thread_hammer():
    """16 threads hammer a histogram + a labeled counter while scrapers
    render concurrently: every rendered histogram row must be internally
    consistent (sum == count * observed value, +Inf cumulative == count) —
    the torn-row bug one-locked-snapshot-per-scrape fixes."""
    reg = Registry()
    hist = reg.histogram("hammer_seconds", "h", buckets=(0.5, 1.0, 2.0))
    ctr = reg.counter("hammer_total", "c", ("worker",))
    stop = threading.Event()

    def worker(i):
        child = ctr.labels(worker=str(i))
        while not stop.is_set():
            hist.observe(1.0)  # sum must always equal count * 1.0
            child.inc(3.0)     # rows must always be multiples of 3

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in ts:
        t.start()
    torn = []
    try:
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            for text in (reg.render_text(),):
                inf = cnt = hsum = None
                for line in text.splitlines():
                    if line.startswith('hammer_seconds_bucket{le="+Inf"}'):
                        inf = float(line.split()[-1])
                    elif line.startswith("hammer_seconds_sum"):
                        hsum = float(line.split()[-1])
                    elif line.startswith("hammer_seconds_count"):
                        cnt = float(line.split()[-1])
                    elif line.startswith("hammer_total{"):
                        v = float(line.split()[-1])
                        if v % 3.0 != 0.0:
                            torn.append(("counter", line))
                if inf != cnt:
                    torn.append(("inf!=count", inf, cnt))
                if hsum != cnt:
                    torn.append(("sum!=count*1.0", hsum, cnt))
            # the JSON snapshot path must be consistent too (/debug/vars)
            snap = reg.snapshot()["hammer_seconds"]["samples"][0]
            if snap["buckets"][-1][1] + sum(
                    c for _, c in snap["buckets"][:-1]) != snap["count"]:
                torn.append(("snapshot buckets", snap))
            if snap["sum"] != snap["count"] * 1.0:
                torn.append(("snapshot sum", snap))
    finally:
        stop.set()
        for t in ts:
            t.join()
    assert not torn, torn[:5]


# ------------------------------------------------------------- sampler -------


def test_sampler_pools_and_clean_shutdown():
    img = make_image()
    sc = scope.enable(sampler=False)
    sampler = scope.RuntimeSampler(sc, interval_s=30.0)
    sampler.start()
    try:
        sampler.sample_once()
        pools = {s["labels"]["pool"]: s["value"]
                 for s in __import__(
                     "open_simulator_tpu.obs.instruments",
                     fromlist=["x"]).SCOPE_POOL_BYTES.samples()}
        assert pools.get("image_tables", 0) > 0, pools
        assert "carry_cache" in pools
        tracks = [e for e in sc.events() if e.get("ph") == "C"]
        names = {e["name"] for e in tracks}
        assert {"device_pool_bytes", "compile_cache_delta",
                "transfer_bytes_per_s"} <= names
    finally:
        sampler.stop()
    assert not sampler.alive
    assert not any(t.name == "simon-scope-sampler"
                   for t in threading.enumerate())
    # keep a reference so the image's pools stay registered during the test
    assert img.device_pool_bytes()["image_tables"] > 0


def test_trace_buffer_cap_drops_and_counts():
    sc = scope.enable(trace_cap=4)
    for i in range(8):
        sc.emit_span(f"s{i}", 0.0, 1.0)
    assert len(sc.events()) == 4
    dropped = sum(s["value"] for s in __import__(
        "open_simulator_tpu.obs.instruments",
        fromlist=["x"]).SCOPE_TRACE_DROPPED.samples())
    assert dropped >= 4


def test_chrome_trace_shape():
    sc = scope.enable()
    with sc.request_span("unit"):
        with sc.span("inner", cat="serve"):
            pass
    doc = sc.chrome_trace(metrics={"m": 1})
    assert doc["displayTimeUnit"] == "ms"
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"request:unit", "inner"} <= names
    assert doc["metadata"]["metrics"] == {"m": 1}
    assert "slo" in doc["metadata"]
    json.dumps(doc)  # perfetto-loadable == valid JSON at minimum
